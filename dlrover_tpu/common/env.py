"""Environment helpers for node/process identity.

Reference parity: ``dlrover/python/common/env_utils.py``.
"""

import os

from dlrover_tpu.common.constants import NodeEnv


def _get_int(name: str, default: int = 0) -> int:
    value = os.getenv(name, "")
    try:
        return int(value)
    except (TypeError, ValueError):
        return default


def get_node_id() -> int:
    return _get_int(NodeEnv.NODE_ID, 0)


def get_node_rank() -> int:
    return _get_int(NodeEnv.NODE_RANK, get_node_id())


def get_node_num() -> int:
    return _get_int(NodeEnv.NODE_NUM, 1)


def get_node_type() -> str:
    return os.getenv(NodeEnv.NODE_TYPE, "worker")


def get_process_rank() -> int:
    return _get_int(NodeEnv.PROCESS_RANK, 0)


def get_process_count() -> int:
    return _get_int(NodeEnv.PROCESS_COUNT, 1)


def get_local_rank() -> int:
    return _get_int(NodeEnv.LOCAL_RANK, 0)


def get_local_process_count() -> int:
    return _get_int(NodeEnv.LOCAL_PROCESS_COUNT, 1)


def get_master_addr() -> str:
    return os.getenv(NodeEnv.MASTER_ADDR, "")


def get_job_name() -> str:
    return os.getenv(NodeEnv.JOB_NAME, "local-job")


def get_restart_count() -> int:
    return _get_int(NodeEnv.RESTART_COUNT, 0)


INPUT_PIPELINE_ENV = "DLROVER_TPU_INPUT_PIPELINE"


def input_pipeline_enabled() -> bool:
    """Kill-switch for the pipelined input plane (background host
    fetch in ``ElasticDataLoader``/``device_prefetch`` and the
    shard-task RPC prefetch).  ``DLROVER_TPU_INPUT_PIPELINE=0``
    reproduces the serial path — same batch order, byte-identical
    batches (pinned by tests).  Default: enabled."""
    return os.getenv(INPUT_PIPELINE_ENV, "1").lower() not in (
        "0", "false", "off",
    )


CONTROL_LONGPOLL_ENV = "DLROVER_TPU_CONTROL_LONGPOLL"
CONTROL_BATCH_ENV = "DLROVER_TPU_CONTROL_BATCH"
DATASTORE_SYNC_ENV = "DLROVER_TPU_DATASTORE_SYNC"


def control_longpoll_enabled() -> bool:
    """Kill-switch for the control-plane fast path: server-side
    long-poll waits (KV store, comm world, shard tasks, training
    status, master-ready).  ``DLROVER_TPU_CONTROL_LONGPOLL=0``
    reproduces the client-side polling loops exactly (the bench
    reference and the rollback path).  Default: enabled."""
    return os.getenv(CONTROL_LONGPOLL_ENV, "1").lower() not in (
        "0", "false", "off",
    )


def control_batch_enabled() -> bool:
    """Kill-switch for coalesced delta reporting: with
    ``DLROVER_TPU_CONTROL_BATCH=0`` every ``ReportBuffer.add``
    degenerates to the old one-RPC-per-report path.  Default:
    enabled."""
    return os.getenv(CONTROL_BATCH_ENV, "1").lower() not in (
        "0", "false", "off",
    )


def datastore_sync_enabled() -> bool:
    """``DLROVER_TPU_DATASTORE_SYNC=1`` keeps every Brain datastore
    write a synchronous INSERT+commit (today's behavior, byte-for-byte
    — pinned by tests); default is the write-behind flusher."""
    return os.getenv(DATASTORE_SYNC_ENV, "").lower() in (
        "1", "true", "on",
    )


OBSERVATORY_ENV = "DLROVER_TPU_OBSERVATORY"
EVENTS_MAX_MB_ENV = "DLROVER_TPU_EVENTS_MAX_MB"
TIMELINE_MAX_AGE_ENV = "DLROVER_TPU_TIMELINE_MAX_AGE_S"
TIMELINE_MAX_ROWS_ENV = "DLROVER_TPU_TIMELINE_MAX_ROWS"


def observatory_enabled() -> bool:
    """Kill-switch for the master-side observatory: the streaming
    health-derivation engine (``observability/health.py``), the
    derived-signal diagnosis operators (straggler / data-stall / hang
    watchdog), the ``JobStatusRequest`` RPC, the ``--status_port``
    HTTP endpoints, and the timeline growth bounds (agent JSONL
    rotation + Brain retention sweep).  ``DLROVER_TPU_OBSERVATORY=0``
    reproduces today's paths exactly: the private
    ``DiagnosisDataStore`` chain alone, SpeedMonitor-only hang
    detection, unbounded timeline growth.  Default: enabled."""
    return os.getenv(OBSERVATORY_ENV, "1").lower() not in (
        "0", "false", "off",
    )


def env_float(name: str, default: float) -> float:
    """Float env knob with a default (malformed values fall back) —
    the one parser behind every tunable threshold."""
    try:
        return float(os.getenv(name, "") or default)
    except ValueError:
        return default


def events_max_bytes() -> int:
    """Size-based rotation threshold for the agent-side JSONL events
    file (0 = never rotate).  Generous default: a week-long job at
    control-plane event rates stays far below it."""
    return int(env_float(EVENTS_MAX_MB_ENV, 256.0) * 1024 * 1024)


def timeline_max_age_s() -> float:
    """Brain ``timeline_events`` retention age (rows older than this
    are swept; 0 = age-unbounded)."""
    return env_float(TIMELINE_MAX_AGE_ENV, 7 * 24 * 3600.0)


def timeline_max_rows() -> int:
    """Brain ``timeline_events`` per-job row cap (newest rows win;
    0 = row-unbounded)."""
    return int(env_float(TIMELINE_MAX_ROWS_ENV, 500_000))


RESHARD_ENV = "DLROVER_TPU_RESHARD"
CKPT_CLOSE_TIMEOUT_ENV = "DLROVER_TPU_CKPT_CLOSE_TIMEOUT_S"
PREEMPT_DRAIN_GRACE_ENV = "DLROVER_TPU_PREEMPT_DRAIN_GRACE_S"


def reshard_enabled() -> bool:
    """Kill-switch for the elastic-reshard subsystem: device-count-
    agnostic layout headers on checkpoint shards, the overlap-range
    resharded restore leg in ``CheckpointEngine``, the agent's
    graceful worker drain (SIGUSR1 snapshot-every-step + SIGTERM
    drain-then-flush) and the ``node_preempted`` master fencing.
    ``DLROVER_TPU_RESHARD=0`` reproduces today's behavior exactly: a
    world-size change restores per-rank shard files or fails, the
    SIGTERM path is the bare ckpt_saver flush, and preemption reports
    stay ``node_error``.  Default: enabled."""
    return os.getenv(RESHARD_ENV, "1").lower() not in (
        "0", "false", "off",
    )


def ckpt_close_timeout_s() -> float:
    """How long ``CheckpointEngine.close()`` waits for an in-flight
    snapshot drain before deliberately LEAKING the shm/lock/queue
    handles (closing under a live drain would corrupt the persist —
    the leak is the safe outcome, now observable via the
    ``dlrover_tpu_ckpt_drain_stuck`` counter)."""
    return env_float(CKPT_CLOSE_TIMEOUT_ENV, 300.0)


def preempt_drain_grace_s() -> float:
    """How long the agent waits, after asking workers to drain
    (SIGUSR1 -> snapshot-every-step), for a fresh common step to land
    in shm before flushing to storage.  Bounded by the preemption
    notice lead (~60 s on GCE) and the pod's SIGTERM grace."""
    return env_float(PREEMPT_DRAIN_GRACE_ENV, 5.0)


SERVING_ENV = "DLROVER_TPU_SERVING"
GEN_TIMEOUT_ENV = "DLROVER_TPU_GEN_TIMEOUT_S"
GEN_CLOSE_TIMEOUT_ENV = "DLROVER_TPU_GEN_CLOSE_TIMEOUT_S"
GEN_BUCKETS_ENV = "DLROVER_TPU_GEN_BUCKETS"
GEN_BATCHED_PREFILL_ENV = "DLROVER_TPU_GEN_BATCHED_PREFILL"
SERVING_DRAIN_ENV = "DLROVER_TPU_SERVING_DRAIN_S"


def serving_enabled() -> bool:
    """Kill-switch for the continuous-batching inference plane
    (``rl/scheduler.py`` + the multi-replica dispatcher in
    ``rl/generation_service.py``).  ``DLROVER_TPU_SERVING=0``
    reproduces today's single-worker request/queue loop exactly
    (``make_generation_engine`` returns the legacy engine; pinned by
    tests).  Default: enabled."""
    return os.getenv(SERVING_ENV, "1").lower() not in (
        "0", "false", "off",
    )


def gen_timeout_s() -> float:
    """Per-request response timeout of the cross-process generation
    engines (was a hard-coded 600 s in
    ``CrossProcessGenerationEngine.generate``)."""
    return env_float(GEN_TIMEOUT_ENV, 600.0)


def gen_close_timeout_s() -> float:
    """How long generation-engine ``close()`` waits for the worker's
    stop handshake / process exit before killing it (the
    ``DLROVER_TPU_CKPT_CLOSE_TIMEOUT_S`` pattern)."""
    return env_float(GEN_CLOSE_TIMEOUT_ENV, 30.0)


def gen_buckets() -> tuple:
    """Prompt-length buckets for the generation backends: prompts pad
    up to the smallest bucket >= their length, so
    ``JitSamplerBackend`` / ``KVCacheBackend`` compile once per
    (batch, BUCKET) instead of once per distinct ``[B, P]``.  Causal
    masking makes the padded result identical to the exact-shape one
    at any temperature (the batch dim — which shapes the sampler's
    noise — is never padded).  Unset/empty = exact shapes (today's
    behavior)."""
    raw = os.getenv(GEN_BUCKETS_ENV, "")
    out = []
    for part in raw.replace(";", ",").split(","):
        part = part.strip()
        if part:
            try:
                out.append(int(part))
            except ValueError:
                continue  # junk entries are ignored, not fatal
    return tuple(sorted(set(b for b in out if b > 0)))


def gen_batched_prefill_enabled() -> bool:
    """Kill-switch for ``KVCacheBackend``'s batched single-forward
    prefill; ``0`` restores the one-token-at-a-time ``lax.scan``
    prefill exactly."""
    return os.getenv(GEN_BATCHED_PREFILL_ENV, "1").lower() not in (
        "0", "false", "off",
    )


def serving_drain_grace_s() -> float:
    """How long a draining serving replica keeps stepping to flush
    responses before handing unfinished sequences back to the
    dispatcher (SIGUSR1/SIGTERM drain protocol)."""
    return env_float(SERVING_DRAIN_ENV, 2.0)


SERVE_OBS_ENV = "DLROVER_TPU_SERVE_OBS"


def serve_obs_enabled() -> bool:
    """Kill-switch for the serving observatory (ISSUE 16): per-request
    lifecycle spans (``serve_request``/``queue_wait``/``admit``/
    ``resume``), the per-replica TTFT/TBT/e2e/queue-wait SLO
    histograms on ``/metrics``, and the ``ServingHealthEngine``
    derivations (SLO-straggler score, dead-air watchdog, KV-pressure
    streaks).  ``DLROVER_TPU_SERVE_OBS=0`` reproduces the PR-14
    serving surfaces byte-for-byte — no new spans, gauges, histogram
    series, or status keys (pinned by tests).  Default: enabled."""
    return os.getenv(SERVE_OBS_ENV, "1").lower() not in (
        "0", "false", "off",
    )


SERVE_FLEET_ENV = "DLROVER_TPU_SERVE_FLEET"
FLEET_IMBALANCE_ENV = "DLROVER_TPU_FLEET_IMBALANCE_CAP"
FLEET_INTERACTIVE_SLOTS_ENV = "DLROVER_TPU_FLEET_INTERACTIVE_SLOTS"
FLEET_PREFILL_WORKERS_ENV = "DLROVER_TPU_FLEET_PREFILL_WORKERS"
FLEET_SHIP_SLOTS_ENV = "DLROVER_TPU_FLEET_SHIP_SLOTS"
FLEET_MIN_SHIP_PROMPT_ENV = "DLROVER_TPU_FLEET_MIN_SHIP_PROMPT"


def serve_fleet_enabled() -> bool:
    """Kill-switch for the fleet-level serving layer (ISSUE 17):
    prefix-affinity routing in the dispatcher (per-replica shared-block
    key index piggybacked on the STATS ring), SLO-class lanes with
    per-tenant fair-share admission + class-aware preemption in the
    scheduler, and the disaggregated prefill/decode split with shm KV
    block shipping.  ``DLROVER_TPU_SERVE_FLEET=0`` reproduces the
    PR-16 surfaces exactly: least-outstanding routing, single-class
    FIFO admission, no ship spans, no fleet gauges (pinned by tests).
    Default: enabled."""
    return os.getenv(SERVE_FLEET_ENV, "1").lower() not in (
        "0", "false", "off",
    )


def fleet_imbalance_cap() -> int:
    """Affinity routing's load-imbalance cap: an affinity-preferred
    replica is eligible only while its outstanding count stays within
    this many requests of the least-loaded live replica — affinity may
    bias placement but never starve a replica (>= 1)."""
    return max(1, int(env_float(FLEET_IMBALANCE_ENV, 4)))


def fleet_interactive_slots() -> int:
    """Reserved decode-slot quota for the interactive SLO class: batch
    admission leaves at least this many of ``max_slots`` free for
    interactive lanes (clamped to ``max_slots - 1`` at use so batch
    can always make progress; 0 = no reservation)."""
    return max(0, int(env_float(FLEET_INTERACTIVE_SLOTS_ENV, 2)))


def fleet_prefill_workers() -> int:
    """How many replicas the dispatcher designates as PREFILL workers
    (disaggregated prefill/decode).  They fill KV blocks and ship them
    over shm to decode replicas; 0 (the default) keeps every replica
    unified.  Clamped so at least one decode replica remains."""
    return max(0, int(env_float(FLEET_PREFILL_WORKERS_ENV, 0)))


def fleet_ship_slots() -> int:
    """Slots in the dispatcher-owned shm ship arena (concurrent
    in-flight prefill->decode block transfers; >= 1)."""
    return max(1, int(env_float(FLEET_SHIP_SLOTS_ENV, 8)))


def fleet_min_ship_prompt() -> int:
    """Minimum prompt length (tokens) for a request to take the
    disaggregated prefill->ship->decode path; shorter prompts go
    straight to a decode replica (prefilling them locally costs less
    than a block ship).  0 = ship everything."""
    return max(0, int(env_float(FLEET_MIN_SHIP_PROMPT_ENV, 0)))


KV_INCREMENTAL_ENV = "DLROVER_TPU_KV_INCREMENTAL"
KV_GROW_BLOCKS_ENV = "DLROVER_TPU_KV_GROW_BLOCKS"
KV_ADMIT_WATERMARK_ENV = "DLROVER_TPU_KV_ADMIT_WATERMARK"
KV_PREFIX_CACHE_ENV = "DLROVER_TPU_KV_PREFIX_CACHE"
DECODE_STEPS_ENV = "DLROVER_TPU_DECODE_STEPS"


def kv_incremental_enabled() -> bool:
    """Kill-switch for the incremental-allocation serving discipline
    (watermark admission + on-demand block growth + lowest-priority
    sequence preemption + prefix caching in ``rl/scheduler.py`` /
    ``rl/kv_cache.py``).  ``DLROVER_TPU_KV_INCREMENTAL=0`` reproduces
    the PR-13 worst-case reservation admission byte-for-byte (admit
    only when ``ceil((prompt + max_new) / block_size)`` blocks are
    free; no growth, no preemption, no shared blocks — pinned by
    tests).  Default: enabled."""
    return os.getenv(KV_INCREMENTAL_ENV, "1").lower() not in (
        "0", "false", "off",
    )


def kv_grow_blocks() -> int:
    """Decode-time growth quantum: how many blocks an admitted
    sequence reserves as headroom beyond its prompt, and the chunk its
    block table grows by when decode crosses a block boundary (>= 1 —
    the first decode position can sit past the prompt's last block)."""
    return max(1, int(env_float(KV_GROW_BLOCKS_ENV, 2)))


def kv_admit_watermark() -> float:
    """Watermark admission (incremental mode): a new sequence is
    admitted only if, after its initial allocation, at least this
    FRACTION of the usable pool stays free as growth headroom for the
    sequences already running.  0 = admit whenever the initial
    allocation fits (maximum admission, maximum preemption churn).
    The first sequence always admits regardless (progress)."""
    return min(max(env_float(KV_ADMIT_WATERMARK_ENV, 0.1), 0.0), 0.9)


def kv_prefix_cache_enabled() -> bool:
    """Prefix caching (incremental mode only): content-hash full
    prompt blocks into a ref-counted shared-block index so requests
    with a common prompt prefix map the same physical blocks.
    ``DLROVER_TPU_KV_PREFIX_CACHE=0`` disables sharing while keeping
    incremental allocation.  Default: enabled (inert unless
    ``kv_incremental_enabled()``)."""
    return os.getenv(KV_PREFIX_CACHE_ENV, "1").lower() not in (
        "0", "false", "off",
    )


def decode_steps() -> int:
    """Multi-token decode: K decode steps fused into ONE compiled
    scheduler iteration (K-greedy self-drafting + one batched verify
    forward; ``rl/scheduler.py``).  ``DLROVER_TPU_DECODE_STEPS=1``
    (the default) is exactly the PR-13 one-token-per-dispatch loop."""
    return max(1, int(env_float(DECODE_STEPS_ENV, 1)))


PROFILE_ENV = "DLROVER_TPU_PROFILE"
PROFILE_EVERY_ENV = "DLROVER_TPU_PROFILE_EVERY_N_STEPS"
CAPTURE_STEPS_ENV = "DLROVER_TPU_CAPTURE_STEPS"
CAPTURE_COOLDOWN_ENV = "DLROVER_TPU_CAPTURE_COOLDOWN_S"
CAPTURE_TIMEOUT_ENV = "DLROVER_TPU_CAPTURE_TIMEOUT_S"
CAPTURE_DIR_ENV = "DLROVER_TPU_CAPTURE_DIR"


def profile_enabled() -> bool:
    """Kill-switch for the live attribution profiler: the continuous
    ``step_profile`` leg in the trainer, the per-node MFU /
    device-share derivations + gauges in the ``HealthEngine``, the
    master's ``CaptureCoordinator`` (diagnosis-triggered deep
    captures riding the directive piggyback), the worker-side capture
    signal handler, and the Brain ``profiles`` surface.
    ``DLROVER_TPU_PROFILE=0`` reproduces today's paths exactly: no
    ``step_profile`` spans, no mfu/device-share gauges, no ``capture``
    directives on the wire (pinned by tests).  Default: enabled —
    though the continuous leg additionally needs
    ``DLROVER_TPU_PROFILE_EVERY_N_STEPS`` > 0 (default 0 = off, zero
    per-step overhead)."""
    return os.getenv(PROFILE_ENV, "1").lower() not in (
        "0", "false", "off",
    )


def profile_every_n_steps() -> int:
    """Continuous-leg cadence: every N steps the trainer captures a
    one-step ``jax.profiler`` trace and emits a ``step_profile`` span
    (0 = off; the default, so the always-on claim costs nothing until
    an operator opts in)."""
    return max(int(env_float(PROFILE_EVERY_ENV, 0.0)), 0)


def capture_steps() -> int:
    """How many consecutive steps a deep capture traces."""
    return max(int(env_float(CAPTURE_STEPS_ENV, 3.0)), 1)


def capture_cooldown_s() -> float:
    """Per-node throttle on diagnosis-triggered deep captures: the
    hang-watchdog / sustained-straggler conclusions auto-trigger at
    most ONE capture of a node per this window."""
    return env_float(CAPTURE_COOLDOWN_ENV, 600.0)


def capture_timeout_s() -> float:
    """How long the agent waits for its workers' profile artifacts
    after the capture signal before shipping what it has (a hung
    worker never answers — its stack dump is the artifact)."""
    return env_float(CAPTURE_TIMEOUT_ENV, 15.0)


def capture_dir() -> str:
    """Where capture artifacts (stack dumps, trace summaries) land:
    ``DLROVER_TPU_CAPTURE_DIR``, else a ``captures/`` dir next to the
    node's events file, else "" (no capture surface)."""
    d = os.getenv(CAPTURE_DIR_ENV, "")
    if d:
        return d
    events_file = os.getenv("DLROVER_TPU_EVENTS_FILE", "")
    if events_file:
        return os.path.join(
            os.path.dirname(os.path.abspath(events_file)), "captures"
        )
    return ""


BRAIN_ENV = "DLROVER_TPU_BRAIN"
BRAIN_INTERVAL_ENV = "DLROVER_TPU_BRAIN_INTERVAL_S"
BRAIN_COOLDOWN_ENV = "DLROVER_TPU_BRAIN_COOLDOWN_S"
BRAIN_SUSTAIN_ENV = "DLROVER_TPU_BRAIN_SUSTAIN"


def brain_enabled() -> bool:
    """Kill-switch for the autonomy loop: the observatory-fed Brain
    (``master/resource_optimizer.ObservatoryBrainOptimizer`` +
    ``master/auto_scaler.BrainAutoScaler`` + the planned-action
    executor in ``master/brain.py``), its node directives riding the
    ``WaitingNodeNum`` response, its journal component, and the
    ``scale_decision``/``scale_execute`` telemetry.
    ``DLROVER_TPU_BRAIN=0`` reproduces the seed auto-scaler exactly:
    ``AllreduceAutoScaler`` polling the ``SpeedMonitor`` with
    ``Scaler.scale(plan)`` as its only actuator, no directives on the
    wire, nothing journaled.  Default: enabled."""
    return os.getenv(BRAIN_ENV, "1").lower() not in (
        "0", "false", "off",
    )


def brain_interval_s() -> float:
    """Cadence of the Brain decision cycle."""
    return env_float(BRAIN_INTERVAL_ENV, 30.0)


def brain_cooldown_s() -> float:
    """Minimum quiet time after an executed decision before the next
    same-direction decision; opposite-direction decisions wait twice
    this (hysteresis)."""
    return env_float(BRAIN_COOLDOWN_ENV, 120.0)


def brain_sustain_cycles() -> int:
    """Consecutive decision cycles a signal (straggler verdict, hang
    verdict, chronic stall share) must persist before the Brain acts
    on it — one noisy snapshot is not a verdict."""
    return max(int(env_float(BRAIN_SUSTAIN_ENV, 2.0)), 1)


SELF_OBS_ENV = "DLROVER_TPU_SELF_OBS"
MASTER_WORKERS_ENV = "DLROVER_TPU_MASTER_WORKERS"


def self_obs_enabled() -> bool:
    """Kill-switch for the master's control-plane SELF-telemetry: the
    per-RPC-kind latency / request-size / response-size histograms,
    the in-flight / parked-long-poll / thread-pool-occupancy gauges,
    the per-job state row counts, the datastore write-behind health
    gauges (queue depth, flush-latency histogram, journal lag), the
    snapshot age/duration gauges, the ``master`` section of
    ``/status`` + ``JobStatusResponse``, and the ``MasterHealth``
    overload deriver.  ``DLROVER_TPU_SELF_OBS=0`` reproduces the
    pre-self-obs metric surface exactly — no ``dlrover_tpu_master_*``
    / ``dlrover_tpu_datastore_*`` / ``dlrover_tpu_journal_*`` /
    ``dlrover_tpu_snapshot_*`` series exist (pinned by tests).
    Default: enabled."""
    return os.getenv(SELF_OBS_ENV, "1").lower() not in (
        "0", "false", "off",
    )


def master_workers() -> int:
    """gRPC thread-pool size of the master server
    (``DLROVER_TPU_MASTER_WORKERS``).  Each PARKED long-poll holds a
    pool thread for its whole wait, so the ceiling bounds the fleet a
    single master can serve — it must be raisable without a code
    change, and the occupancy gauge
    (``dlrover_tpu_master_busy_workers`` over
    ``dlrover_tpu_master_worker_pool_size``) is derived from this
    same value so the two can never disagree."""
    return max(int(env_float(MASTER_WORKERS_ENV, 64.0)), 1)


MASTER_FAILOVER_ENV = "DLROVER_TPU_MASTER_FAILOVER"
RECONNECT_DEADLINE_ENV = "DLROVER_TPU_MASTER_RECONNECT_DEADLINE_S"
SNAPSHOT_INTERVAL_ENV = "DLROVER_TPU_CONTROL_SNAPSHOT_INTERVAL_S"


def master_failover_enabled() -> bool:
    """Kill-switch for the master-failover subsystem: durable
    control-plane journaling/replay, transparent ``MasterChannel``
    reconnection, and ``(job_epoch, master_incarnation)`` fencing.
    ``DLROVER_TPU_MASTER_FAILOVER=0`` reproduces the fail-fast
    behavior exactly: a dead master raises ``ConnectionError`` after
    ``max_retry`` attempts, no epochs ride the envelope, and the
    master journals nothing.  Default: enabled."""
    return os.getenv(MASTER_FAILOVER_ENV, "1").lower() not in (
        "0", "false", "off",
    )


def master_reconnect_deadline_s() -> float:
    """Total time a client keeps retrying/reconnecting across a
    master outage before giving up (failover mode only)."""
    try:
        return float(os.getenv(RECONNECT_DEADLINE_ENV, "120"))
    except ValueError:
        return 120.0


def control_snapshot_interval_s() -> float:
    """Cadence of the master's compacted control-plane snapshot
    (journal entries at or below the snapshot seq are pruned)."""
    try:
        return float(os.getenv(SNAPSHOT_INTERVAL_ENV, "20"))
    except ValueError:
        return 20.0


FLYWHEEL_ENV = "DLROVER_TPU_FLYWHEEL"
FLYWHEEL_STALENESS_ENV = "DLROVER_TPU_FLYWHEEL_STALENESS"
FLYWHEEL_MAX_LAG_ENV = "DLROVER_TPU_FLYWHEEL_MAX_LAG"
FLYWHEEL_PUBLISH_EVERY_ENV = "DLROVER_TPU_FLYWHEEL_PUBLISH_EVERY"
FLYWHEEL_DRAFT_ENV = "DLROVER_TPU_FLYWHEEL_DRAFT"
FLYWHEEL_LEND_QUEUE_ENV = "DLROVER_TPU_FLYWHEEL_LEND_QUEUE"
FLYWHEEL_RECLAIM_QUEUE_ENV = "DLROVER_TPU_FLYWHEEL_RECLAIM_QUEUE"
FLYWHEEL_MIN_TRAIN_ENV = "DLROVER_TPU_FLYWHEEL_MIN_TRAIN_WORLD"


def flywheel_enabled() -> bool:
    """Kill-switch for the zero-copy RLHF flywheel (ISSUE 20): the
    in-place K-step weight publish into the shm snapshot segment
    (generation-stamped header + replica-side adopt-if-changed), the
    shm trajectory ring feeding rollouts back as ready training
    batches, the separate published DRAFT model for speculative
    decode, and the Brain's ``FlywheelOperator`` train/serve device
    arbitration.  ``DLROVER_TPU_FLYWHEEL=0`` reproduces today's
    separate planes byte-for-byte: unconditional ``get_step()``
    adoption polling, self-drafting speculative decode, no trajectory
    ring, no plane-labeled scale decisions (pinned by tests).
    Default: enabled."""
    return os.getenv(FLYWHEEL_ENV, "1").lower() not in (
        "0", "false", "off",
    )


def flywheel_staleness_policy() -> str:
    """What happens to a trajectory whose generation lags the current
    published weights by more than ``flywheel_max_lag()``: ``drop``
    (the default — off-policy beyond the lag bound is discarded and
    counted in ``dlrover_tpu_flywheel_staleness_dropped``) or ``tag``
    (kept, with the lag recorded so the learner can importance-weight
    it)."""
    val = os.getenv(FLYWHEEL_STALENESS_ENV, "drop").lower()
    return val if val in ("drop", "tag") else "drop"


def flywheel_max_lag() -> int:
    """Maximum generations a trajectory may lag the published weights
    before the staleness policy applies (>= 0; 0 = only on-policy
    trajectories pass untouched)."""
    return max(0, int(env_float(FLYWHEEL_MAX_LAG_ENV, 1)))


def flywheel_publish_every() -> int:
    """K: the trainer publishes policy (and draft) weights into the
    shm snapshot segment every K optimizer steps (>= 1)."""
    return max(1, int(env_float(FLYWHEEL_PUBLISH_EVERY_ENV, 4)))


def flywheel_draft_enabled() -> bool:
    """Whether the flywheel trains + publishes a separate small DRAFT
    model for K-step speculative decode (the PR-14 residual; today
    the model drafts with itself).  Inert unless the serving factory
    supplies draft-model parts.  Default: enabled (under
    ``flywheel_enabled()``)."""
    return os.getenv(FLYWHEEL_DRAFT_ENV, "1").lower() not in (
        "0", "false", "off",
    )


def flywheel_lend_queue_depth() -> float:
    """Rollout-bound threshold: sustained serving queue depth (per
    live replica) at or above this marks the round rollout-bound and
    eligible for a train->serve chip lend."""
    return env_float(FLYWHEEL_LEND_QUEUE_ENV, 4.0)


def flywheel_reclaim_queue_depth() -> float:
    """Learner-bound threshold: sustained serving queue depth (per
    live replica) at or below this, with a lend outstanding, triggers
    the reclaim (drain a replica, rank rejoins the mesh)."""
    return env_float(FLYWHEEL_RECLAIM_QUEUE_ENV, 0.5)


def flywheel_min_train_world() -> int:
    """Floor on the trainer world size during arbitration: the
    FlywheelOperator never lends a chip that would shrink the mesh
    below this (>= 1)."""
    return max(1, int(env_float(FLYWHEEL_MIN_TRAIN_ENV, 1)))


def get_free_port(host: str = "127.0.0.1") -> int:
    import socket

    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as sock:
        sock.bind((host, 0))
        return sock.getsockname()[1]
