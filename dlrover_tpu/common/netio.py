"""Shared length-prefixed TCP wire helpers (replica + coworker data
planes)."""

import socket
import struct

LEN = struct.Struct(">Q")


def recv_exact(conn: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        buf += chunk
    return buf


def recv_line(conn: socket.socket) -> str:
    buf = b""
    while not buf.endswith(b"\n"):
        c = conn.recv(1)
        if not c:
            raise ConnectionError("peer closed mid-line")
        buf += c
    return buf.decode().strip()
