"""Shared length-prefixed TCP wire helpers (replica + coworker data
planes)."""

import socket
import struct

LEN = struct.Struct(">Q")

#: peek window for ``recv_line`` — one line of the text protocols fits
#: comfortably; longer lines just take another peek round
_PEEK_CHUNK = 4096


def recv_exact(conn: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        buf += chunk
    return buf


def recv_line(conn: socket.socket) -> str:
    """Read one ``\\n``-terminated line.

    Buffered via ``MSG_PEEK``: peek at whatever the kernel already
    holds, find the newline, then consume exactly through it — so the
    bytes after the line stay in the kernel buffer for the next
    ``recv_exact`` (wire semantics identical to the old one-byte-per-
    ``recv`` loop, at ~2 syscalls per line instead of ``len(line)``).
    """
    buf = b""
    while True:
        peek = conn.recv(_PEEK_CHUNK, socket.MSG_PEEK)
        if not peek:
            raise ConnectionError("peer closed mid-line")
        idx = peek.find(b"\n")
        # consume exactly the peeked line prefix (peeked bytes are
        # guaranteed readable); never a byte past the newline
        take = idx + 1 if idx >= 0 else len(peek)
        buf += recv_exact(conn, take)
        if idx >= 0:
            return buf.decode().strip()
