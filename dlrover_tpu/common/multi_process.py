"""Cross-process primitives shared between the elastic agent and the
training processes it spawns: a lock, a queue and a dict served over a
unix-domain socket, plus a POSIX shared-memory wrapper that survives the
death of the creating process.

Reference parity: ``dlrover/python/common/multi_process.py:227,348,455,539``
(SharedLock / SharedQueue / SharedDict / SharedMemory).  These primitives
are the substrate of flash checkpoint: training ranks memcpy device state
into shared memory guarded by ``SharedLock`` while the agent-side saver
drains ``SharedQueue`` events and reads tensor metadata from
``SharedDict``.
"""

import os
import pickle
import queue
import socket
import struct
import threading
import time
from multiprocessing import shared_memory
from typing import Dict, Optional

from dlrover_tpu.common.log import default_logger as logger

SOCKET_DIR_ENV = "DLROVER_TPU_SOCKET_DIR"
_DEF_SOCKET_DIR = "/tmp/dlrover_tpu/sockets"

_LEN = struct.Struct("<I")


def _socket_path(name: str) -> str:
    root = os.getenv(SOCKET_DIR_ENV, _DEF_SOCKET_DIR)
    os.makedirs(root, exist_ok=True)
    return os.path.join(root, f"{name}.sock")


def _send_msg(sock: socket.socket, obj):
    data = pickle.dumps(obj)
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed the local socket")
        buf += chunk
    return buf


def _recv_msg(sock: socket.socket):
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, length))


class LocalSocketComm:
    """Base of the shared primitives.

    ``master=True`` (agent side) serves the object over a unix socket;
    ``master=False`` (training-process side) proxies calls to it.
    """

    def __init__(self, name: str, create: bool):
        self._name = name
        self._path = _socket_path(name)
        self._server = create
        self._server_sock: Optional[socket.socket] = None
        self._client_sock: Optional[socket.socket] = None
        self._client_lock = threading.Lock()
        self._stopped = False
        if create:
            self._start_server()

    # -- server side -------------------------------------------------------
    def _start_server(self):
        if os.path.exists(self._path):
            os.unlink(self._path)
        self._server_sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._server_sock.bind(self._path)
        self._server_sock.listen(64)
        thread = threading.Thread(
            target=self._accept_loop, name=f"lsc-{self._name}", daemon=True
        )
        thread.start()

    def _accept_loop(self):
        while not self._stopped:
            try:
                conn, _ = self._server_sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket):
        with conn:
            while not self._stopped:
                try:
                    method, args = _recv_msg(conn)
                except (ConnectionError, EOFError, OSError):
                    return
                try:
                    result = getattr(self, "_do_" + method)(*args)
                    _send_msg(conn, ("ok", result))
                except Exception as e:  # noqa: BLE001 - proxied to client
                    # ship the exception object so the client re-raises
                    # the same type (queue.Empty, queue.Full, ...)
                    try:
                        _send_msg(conn, ("exc", e))
                    except Exception:
                        _send_msg(conn, ("exc", RuntimeError(repr(e))))

    def close(self):
        self._stopped = True
        if self._server_sock:
            try:
                self._server_sock.close()
            finally:
                if os.path.exists(self._path):
                    try:
                        os.unlink(self._path)
                    except OSError:
                        pass
        if self._client_sock:
            self._client_sock.close()
            self._client_sock = None

    # -- client side -------------------------------------------------------
    def _connect(self, timeout: float = 60.0):
        deadline = time.time() + timeout
        while True:
            try:
                sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                sock.connect(self._path)
                self._client_sock = sock
                return
            except OSError:
                if time.time() > deadline:
                    raise TimeoutError(
                        f"cannot connect local service {self._name}"
                    )
                time.sleep(0.1)

    def _call(self, method: str, *args, idempotent: bool = False):
        if self._server:
            return getattr(self, "_do_" + method)(*args)
        with self._client_lock:
            if self._client_sock is None:
                self._connect()
            try:
                _send_msg(self._client_sock, (method, args))
                status, result = _recv_msg(self._client_sock)
            except (ConnectionError, OSError):
                self._client_sock = None
                if not idempotent:
                    # the server may have applied the request before the
                    # connection died; blindly resending would duplicate
                    # a put/acquire — surface the ambiguity instead
                    raise
                # safe to retry reads once (agent may have restarted)
                self._connect()
                _send_msg(self._client_sock, (method, args))
                status, result = _recv_msg(self._client_sock)
        if status == "exc":
            raise result
        return result


class SharedLock(LocalSocketComm):
    """A lock shared between agent and training processes."""

    def __init__(self, name: str, create: bool = False):
        self._lock = threading.Lock() if create else None
        super().__init__("lock_" + name, create)

    def _do_acquire(self, blocking: bool, timeout: float) -> bool:
        if blocking:
            return self._lock.acquire(timeout=timeout)
        return self._lock.acquire(blocking=False)

    def _do_release(self) -> bool:
        try:
            self._lock.release()
            return True
        except RuntimeError:
            return False

    def _do_locked(self) -> bool:
        return self._lock.locked()

    def acquire(self, blocking: bool = True, timeout: float = 600.0) -> bool:
        return self._call("acquire", blocking, timeout)

    def release(self) -> bool:
        return self._call("release")

    def locked(self) -> bool:
        return self._call("locked", idempotent=True)

    def __enter__(self):
        if not self.acquire():
            raise TimeoutError(f"cannot acquire shared lock {self._name}")
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class SharedQueue(LocalSocketComm):
    """A FIFO queue shared between agent and training processes."""

    def __init__(self, name: str, create: bool = False, maxsize: int = 0):
        self._queue: Optional[queue.Queue] = (
            queue.Queue(maxsize) if create else None
        )
        super().__init__("queue_" + name, create)

    def _do_put(self, obj, block: bool, timeout: Optional[float]):
        self._queue.put(obj, block=block, timeout=timeout)

    def _do_get(self, block: bool, timeout: Optional[float]):
        return self._queue.get(block=block, timeout=timeout)

    def _do_qsize(self) -> int:
        return self._queue.qsize()

    def _do_empty(self) -> bool:
        return self._queue.empty()

    def put(self, obj, block: bool = True, timeout: Optional[float] = None):
        return self._call("put", obj, block, timeout)

    def get(self, block: bool = True, timeout: Optional[float] = None):
        return self._call("get", block, timeout)

    def qsize(self) -> int:
        return self._call("qsize", idempotent=True)

    def empty(self) -> bool:
        return self._call("empty", idempotent=True)


class SharedDict(LocalSocketComm):
    """A dict shared between agent and training processes.

    Writers call ``set``/``update``; the agent-side saver reads the whole
    dict with ``get_all``.
    """

    def __init__(self, name: str, create: bool = False):
        self._dict: Optional[Dict] = {} if create else None
        super().__init__("dict_" + name, create)

    def _do_set(self, key, value):
        self._dict[key] = value

    def _do_update(self, other: Dict):
        self._dict.update(other)

    def _do_get(self, key, default=None):
        return self._dict.get(key, default)

    def _do_get_all(self) -> Dict:
        return dict(self._dict)

    def _do_clear(self):
        self._dict.clear()

    def set(self, key, value):
        return self._call("set", key, value)

    def update(self, other: Dict):
        return self._call("update", other)

    def get(self, key, default=None):
        return self._call("get", key, default, idempotent=True)

    def get_all(self) -> Dict:
        return self._call("get_all", idempotent=True)

    def clear(self):
        return self._call("clear")


def _unregister_from_resource_tracker(shm: shared_memory.SharedMemory):
    """Keep the segment alive after this process exits.

    Python's resource tracker unlinks shm segments when the creating
    process dies — exactly what flash checkpoint must prevent (the agent
    reads the segment *after* a training-process crash).  Same trick as
    the reference (``common/multi_process.py:539``).
    """
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")  # type: ignore[attr-defined]
    except Exception:  # pragma: no cover - py-version specific
        logger.warning("cannot unregister shm from resource tracker")


class SharedMemory:
    """POSIX shared memory that outlives its creator.

    A thin wrapper over ``multiprocessing.shared_memory.SharedMemory``
    with resource-tracker unregistration and idempotent create/attach.
    """

    def __init__(self, name: str, create: bool = False, size: int = 0):
        self._name = name
        if create:
            try:
                self._shm = shared_memory.SharedMemory(
                    name=name, create=True, size=size
                )
            except FileExistsError:
                existing = shared_memory.SharedMemory(name=name)
                if existing.size >= size:
                    self._shm = existing
                else:
                    existing.unlink()
                    existing.close()
                    self._shm = shared_memory.SharedMemory(
                        name=name, create=True, size=size
                    )
        else:
            self._shm = shared_memory.SharedMemory(name=name)
        _unregister_from_resource_tracker(self._shm)

    @property
    def name(self) -> str:
        return self._name

    @property
    def buf(self):
        return self._shm.buf

    @property
    def size(self) -> int:
        return self._shm.size

    def close(self):
        self._shm.close()

    def unlink(self):
        try:
            self._shm.unlink()
        except FileNotFoundError:
            pass
