"""Control-plane message dataclasses + serialization envelope.

Reference parity: ``dlrover/python/common/grpc.py:150-496`` — the whole
agent<->master protocol is two RPCs (``report`` fire-and-forget with a
bool ack, ``get`` request/response) carrying serialized dataclasses in an
envelope ``Message{node_id, node_type, data}``
(``dlrover/proto/elastic_training.proto:19-29``).  The full dispatch
tables are reproduced in SURVEY.md Appendix A; every request/report type
there has an equivalent here (TF-PS-only types are kept for parity since
the master-side services are cheap).

Serialization is pickle restricted to the classes registered in this
module (the reference pickles arbitrarily; we at least pin the class
table).
"""

import io
import pickle
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


#: Pinned wire protocol.  ``pickle.dumps`` without a protocol argument
#: uses DEFAULT_PROTOCOL, which lags HIGHEST by a version or two on
#: every interpreter — pinning HIGHEST keeps (de)serialization cost
#: minimal AND makes the choice explicit so the ``BatchedReport``
#: nesting (messages inside a message) can't silently fall back to a
#: slower encoding.  Parity is enforced by a round-trip test over
#: every message type in ``tests/test_control_plane.py``.
WIRE_PICKLE_PROTOCOL = pickle.HIGHEST_PROTOCOL


class Message:
    """Base class; every control-plane dataclass derives from it."""

    def serialize(self) -> bytes:
        return pickle.dumps(self, protocol=WIRE_PICKLE_PROTOCOL)


#: builtins actually needed to unpickle our dataclasses (container and
#: scalar constructors only — never eval/exec/getattr).
_SAFE_BUILTINS = {
    "set",
    "frozenset",
    "bytearray",
    "complex",
    "slice",
    "range",
}
_ALLOWED_MODULE_PREFIXES = ("dlrover_tpu.", "collections")


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        if module == "builtins":
            if name in _SAFE_BUILTINS:
                return super().find_class(module, name)
        elif module.startswith(_ALLOWED_MODULE_PREFIXES):
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"forbidden class in control-plane message: {module}.{name}"
        )


def serialize_message(message: Optional[Message]) -> bytes:
    if message is None:
        return b""
    return pickle.dumps(message, protocol=WIRE_PICKLE_PROTOCOL)


def deserialize_message(data: bytes):
    if not data:
        return None
    return _RestrictedUnpickler(io.BytesIO(data)).load()


@dataclass
class Envelope(Message):
    """The on-wire unit: who sent it + the payload message.

    ``job_epoch`` / ``master_incarnation`` are the failover fencing
    pair: the epoch identifies the JOB generation (stable across
    master restarts of the same job; bumped when the job itself is
    reborn), the incarnation identifies the serving MASTER process
    (bumped on every master start).  ``-1`` = "not speaking the
    fencing protocol" (old clients, or failover kill-switched) and is
    never fenced."""

    node_id: int = 0
    node_type: str = ""
    data: bytes = b""
    job_epoch: int = -1
    master_incarnation: int = -1


@dataclass
class BoolResponse(Message):
    success: bool = False
    reason: str = ""


# --------------------------------------------------------------------------
# `get` requests (master/servicer get-dispatch parity)
# --------------------------------------------------------------------------


@dataclass
class TaskRequest(Message):
    dataset_name: str = ""
    #: long-poll: >0 blocks the master up to this many seconds while
    #: the dataset would only hand out WAIT tasks (0 = classic
    #: immediate answer)
    wait_timeout: float = 0.0


@dataclass
class DataShard(Message):
    name: str = ""
    start: int = 0
    end: int = 0
    record_indices: Optional[List[int]] = None


@dataclass
class Task(Message):
    task_id: int = -1
    task_type: str = ""  # TRAINING / EVALUATION / WAIT / NONE
    shard: DataShard = field(default_factory=DataShard)

    @property
    def is_empty(self) -> bool:
        return self.task_id < 0 and self.task_type != TaskType.WAIT


class TaskType:
    NONE = "none"
    TRAINING = "training"
    EVALUATION = "evaluation"
    PREDICTION = "prediction"
    WAIT = "wait"


@dataclass
class ShardCheckpointRequest(Message):
    dataset_name: str = ""


@dataclass
class ShardCheckpoint(Message):
    dataset_name: str = ""
    content: str = ""  # JSON from DatasetSplitter.checkpoint()


@dataclass
class RunningNodesRequest(Message):
    #: delta protocol: the version of the client's cached copy; the
    #: master answers ``NotModified`` when nothing changed (-1 = always
    #: send the full list)
    version: int = -1


@dataclass
class RunningNodes(Message):
    nodes: List = field(default_factory=list)
    version: int = 0


@dataclass
class JoinRendezvousRequest(Message):
    node_id: int = 0
    node_rank: int = 0
    local_world_size: int = 1
    rdzv_name: str = ""
    node_ip: str = ""


@dataclass
class RendezvousState(Message):
    round: int = 0
    waiting_num: int = 0


@dataclass
class WaitingNodeNumRequest(Message):
    rdzv_name: str = ""
    #: long-poll: >0 blocks until the waiting count differs from
    #: ``last_num`` (or the timeout elapses); 0 = immediate answer
    wait_timeout: float = 0.0
    last_num: int = -1


@dataclass
class WaitingNodeNum(Message):
    waiting_num: int = 0
    #: Brain node directive piggybacked on the monitor-pacing poll
    #: (zero extra RPCs): "" = nothing for this node; ``drain`` = run
    #: the graceful-drain protocol (snapshot → flush → report
    #: preempted → exit) — the Brain planned this node out of the
    #: world.  Consumed on delivery; old masters simply never set it.
    action: str = ""
    action_reason: str = ""
    action_id: int = 0


@dataclass
class NetworkReadyRequest(Message):
    pass


@dataclass
class NetworkCheckResult(Message):
    nodes: List[int] = field(default_factory=list)
    reason: str = ""


@dataclass
class StragglerExistRequest(Message):
    pass


@dataclass
class CommWorldRequest(Message):
    node_id: int = 0
    rdzv_name: str = ""
    #: delta protocol: rendezvous state version of the client's cached
    #: world (-1 = no cache); when the version still matches the master
    #: answers ``NotModified`` instead of re-shipping the world
    version: int = -1
    #: long-poll: >0 blocks until the world is complete AND newer than
    #: ``version`` (or the timeout elapses); 0 = immediate answer
    wait_timeout: float = 0.0


@dataclass
class CommWorld(Message):
    rdzv_name: str = ""
    round: int = 0
    group: int = 0
    world: Dict[int, int] = field(default_factory=dict)  # node_rank -> lws
    version: int = 0


@dataclass
class KeyValuePair(Message):
    key: str = ""
    value: bytes = b""


@dataclass
class KVWaitRequest(Message):
    """Long-poll ``get``: block on the master until ``key`` is set (or
    ``wait_timeout`` elapses — the response then carries an empty
    value).  One RPC replaces a ``timeout/interval`` polling loop."""

    key: str = ""
    wait_timeout: float = 0.0


@dataclass
class KeyValuePairs(Message):
    kvs: Dict[str, bytes] = field(default_factory=dict)


@dataclass
class PsNodesRequest(Message):
    pass


@dataclass
class PsNodes(Message):
    nodes: List = field(default_factory=list)
    new_ps_ready: bool = False
    ps_failure: bool = False


@dataclass
class TrainingStatusRequest(Message):
    #: long-poll: >0 blocks until training has started (or the timeout
    #: elapses); 0 = immediate answer
    wait_timeout: float = 0.0


@dataclass
class TrainingStatus(Message):
    status: int = 3  # TrainingLoopStatus.PENDING


@dataclass
class NotModified(Message):
    """Delta-protocol answer: the client's cached copy (at ``version``)
    is still current — nothing to ship."""

    version: int = 0


@dataclass
class StaleEpoch(Message):
    """Typed fencing answer: the request's ``job_epoch`` does not
    match the serving master's.  Carries the CURRENT pair so the
    client can refresh its caches and re-issue instead of crashing."""

    job_epoch: int = 0
    incarnation: int = 0


@dataclass
class ControlEpochRequest(Message):
    """Fetch the master's current ``(job_epoch, incarnation)`` pair —
    the client-side refresh after a ``StaleEpoch`` answer or a
    reconnect.  Never fenced (it IS the refresh path)."""


@dataclass
class ControlEpoch(Message):
    job_epoch: int = 0
    incarnation: int = 0


@dataclass
class ParallelConfigRequest(Message):
    pass


@dataclass
class DataLoaderConfig(Message):
    dataloader_name: str = ""
    batch_size: int = 0
    num_workers: int = 0
    prefetch_count: int = 0


@dataclass
class OptimizerConfig(Message):
    learning_rate: float = 0.0
    micro_batch_size: int = 0


@dataclass
class ParallelConfig(Message):
    dataloader: DataLoaderConfig = field(default_factory=DataLoaderConfig)
    optimizer: OptimizerConfig = field(default_factory=OptimizerConfig)
    mesh_shape: Dict[str, int] = field(default_factory=dict)
    restart: bool = False


@dataclass
class CheckHardwareResetRequest(Message):
    pass


@dataclass
class ClusterVersionRequest(Message):
    task_type: str = ""
    task_id: int = 0
    version_type: str = ""


@dataclass
class ClusterVersion(Message):
    version: int = 0


@dataclass
class ElasticRunConfigRequest(Message):
    pass


@dataclass
class ElasticRunConfig(Message):
    configs: Dict[str, str] = field(default_factory=dict)


# --------------------------------------------------------------------------
# `report` messages (master/servicer report-dispatch parity)
# --------------------------------------------------------------------------


@dataclass
class BatchedReport(Message):
    """Coalesced delta reporting: one envelope carrying several report
    messages (heartbeats, speed/metric samples, node events, timeline
    batches) accumulated by the client-side ``ReportBuffer``.  The
    master dispatches the items IN ORDER through the ordinary report
    table; the ack is true only when every item succeeded."""

    items: List[Message] = field(default_factory=list)


@dataclass
class DatasetShardParams(Message):
    batch_size: int = 0
    num_epochs: int = 1
    dataset_size: int = 0
    shuffle: bool = False
    num_minibatches_per_shard: int = 2
    dataset_name: str = ""
    task_type: str = TaskType.TRAINING
    storage_type: str = "table"


@dataclass
class ResourceStats(Message):
    cpu_percent: float = 0.0
    memory_mb: int = 0
    tpu_stats: List[Dict] = field(default_factory=list)  # per-chip stats


@dataclass
class ModelInfo(Message):
    num_params: int = 0
    flops_per_step: float = 0.0
    hidden_size: int = 0
    num_layers: int = 0
    seq_len: int = 0
    extra: Dict = field(default_factory=dict)


@dataclass
class GlobalStep(Message):
    step: int = 0
    timestamp: float = 0.0
    elapsed_time_per_step: float = 0.0


@dataclass
class TaskResult(Message):
    dataset_name: str = ""
    task_id: int = 0
    err_message: str = ""


@dataclass
class NodeAddress(Message):
    addr: str = ""
    node_type: str = ""
    node_id: int = 0


@dataclass
class NodeTopology(Message):
    """Interconnect position of a node (outermost level first, e.g.
    superpod/pod/slice) — feeds topology-aware rank sorting
    (reference ``net_topology.py:20`` NodeTopologyMeta)."""

    node_rank: int = 0
    levels: Tuple = ()


@dataclass
class NetworkStatus(Message):
    node_rank: int = 0
    succeeded: bool = False
    elapsed_time: float = 0.0


@dataclass
class NodeEventMessage(Message):
    event_type: str = ""
    node_type: str = ""
    node_id: int = 0
    reason: str = ""


@dataclass
class SyncJoin(Message):
    sync_name: str = ""
    worker_type: str = ""
    worker_id: int = 0


@dataclass
class SyncFinish(Message):
    sync_name: str = ""


@dataclass
class SyncBarrier(Message):
    barrier_name: str = ""
    notify: bool = False


@dataclass
class NodeFailure(Message):
    error_data: str = ""
    level: str = ""
    restart_count: int = 0


@dataclass
class RendezvousParams(Message):
    min_nodes: int = 1
    max_nodes: int = 1
    waiting_timeout: int = 600
    node_unit: int = 1
    joint_timeout: int = 600


@dataclass
class PsReady(Message):
    pass


@dataclass
class HeartBeat(Message):
    timestamp: float = 0.0


@dataclass
class NodeCheckpointState(Message):
    step: int = 0


@dataclass
class DiagnosisReportData(Message):
    data_cls: str = ""
    data_content: str = ""
    node_rank: int = -1


@dataclass
class Event(Message):
    event_type: str = ""
    instance: str = ""
    action: str = ""
    msg: str = ""
    labels: Dict[str, str] = field(default_factory=dict)


@dataclass
class SucceededRequest(Message):
    pass


@dataclass
class TimelineEventsReport(Message):
    """One node's batch of timeline events (the JSONL records from
    ``observability/events.py``, shipped by the agent's
    ``TimelineReporter``) for the master's ``TimelineAggregator``."""

    events: List[Dict] = field(default_factory=list)


@dataclass
class TimelineQueryRequest(Message):
    """Get the master's merged goodput ledger (and optionally the
    newest ``limit`` raw events; 0 = ledger only)."""

    job: str = ""
    limit: int = 0


@dataclass
class TimelineQueryResponse(Message):
    ledger: Dict = field(default_factory=dict)
    events: List[Dict] = field(default_factory=list)
    available: bool = False  # False = no aggregator on this master


@dataclass
class JobStatusRequest(Message):
    """Fetch the master observatory's full derived snapshot: per-node
    health (step-rate/step-time EWMAs, stall shares, straggler scores,
    hang verdicts), the live goodput ledger, and the newest diagnosis
    conclusions.  ``scripts/top.py`` and the chaos scenario read this."""

    job: str = ""
    #: include the newest N diagnosis conclusions (0 = none)
    conclusions: int = 16


@dataclass
class JobStatusResponse(Message):
    #: {"health": HealthEngine.snapshot(), "ledger": ...,
    #:  "conclusions": [...], "speed": {...}, "epoch": {...}}
    status: Dict = field(default_factory=dict)
    available: bool = False  # False = observatory off / absent


@dataclass
class ProfileReport(Message):
    """One node's deep-capture result (the agent answering a
    ``capture`` directive): the parsed profile summary — top ops,
    category shares, GEMM clusters, stack-dump inventory — plus the
    path of the artifact written under the events dir.  The master's
    ``CaptureCoordinator`` exposes it on ``/status`` and persists a
    row to the Brain ``profiles`` table."""

    node_rank: int = -1
    kind: str = "capture"
    reason: str = ""
    capture_id: int = 0
    summary: Dict = field(default_factory=dict)
    artifact: str = ""


@dataclass
class BrainQueryRequest(Message):
    """Query the master's durable Brain datastore (speed history /
    node events / measured workloads) — the TPU analog of the Go
    Brain's query RPCs over its MySQL recorders."""

    # speed | node_events | workloads | measurements (the last
    # returns calibration history for ``workload`` — what lets a
    # DIFFERENT job's master adopt this fleet's measurements over RPC
    # instead of mounting the db file)
    kind: str = "speed"
    job: str = "default"
    limit: int = 100
    workload: str = ""  # measurements: a workload_signature string


@dataclass
class BrainQueryResponse(Message):
    # speed: {worker_count: records_per_sec}; node_events: list of
    # dicts; workloads: list of workload-signature strings
    payload: Dict = field(default_factory=dict)
    available: bool = False  # False = no datastore configured


# --------------------------------------------------------------------------
# scale plans (master -> scaler; also CRD-shaped for the k8s path)
# --------------------------------------------------------------------------


@dataclass
class ScalePlan(Message):
    node_group_resources: Dict = field(default_factory=dict)
    launch_nodes: List = field(default_factory=list)
    remove_nodes: List = field(default_factory=list)
    migrate_nodes: Dict = field(default_factory=dict)

    def is_empty(self) -> bool:
        return not (
            self.node_group_resources
            or self.launch_nodes
            or self.remove_nodes
            or self.migrate_nodes
        )
