"""Content-hash-keyed rebuild check for the native components.

Build outputs live under (gitignored) ``native/*/build``; binaries are
rebuilt on first use.  The staleness check is keyed on a source content
hash written to a ``<binary>.srchash`` stamp — mtimes are unreliable
after git checkouts, which reset them unpredictably.
"""

import hashlib
import os


def _source_hash(src_path: str) -> str:
    h = hashlib.sha256()
    with open(src_path, "rb") as f:
        h.update(f.read())
    return h.hexdigest()


def needs_rebuild(binary_path: str, src_path: str) -> bool:
    if not os.path.exists(binary_path):
        return True
    stamp = binary_path + ".srchash"
    try:
        with open(stamp) as f:
            return f.read().strip() != _source_hash(src_path)
    except OSError:
        return True


def write_stamp(binary_path: str, src_path: str) -> None:
    with open(binary_path + ".srchash", "w") as f:
        f.write(_source_hash(src_path))
