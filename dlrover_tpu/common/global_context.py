"""Global master configuration singleton.

Reference parity: ``dlrover/python/common/global_context.py`` — tunables
the master consults everywhere; defaults may be overwritten from env or
(later) a cluster brain service.
"""

import os

from dlrover_tpu.common.constants import JobConstant
from dlrover_tpu.common.singleton import Singleton


class Context(Singleton):
    def __init__(self):
        self.master_port = 0
        self.train_speed_record_num = 50
        self.seconds_to_wait_failed_ps = 600
        self.seconds_for_stable_worker_count = 60
        self.seconds_interval_to_optimize = 300
        self.seconds_interval_to_change_ps = 3600
        self.step_to_adjust_worker = 200
        self.hang_detection_secs = 1800
        self.hang_downtime_secs = 300
        self.seconds_to_timeout_task = 1800
        self.relaunch_always = False
        self.max_node_relaunch_times = 3
        self.relaunch_on_worker_failure = 3
        self.master_service_timeout = JobConstant.MASTER_CLIENT_TIMEOUT
        self.node_heartbeat_timeout = JobConstant.NODE_HEARTBEAT_TIMEOUT
        self.pending_timeout_secs = 900
        self.auto_tune_parallelism = False
        self.is_tfv1_ps = False
        self.remove_exited_node = True
        self.checkpoint_replica = False
        self.load_env()

    def load_env(self):
        self.hang_detection_secs = int(
            os.getenv("DLROVER_TPU_HANG_DETECTION_SECS",
                      self.hang_detection_secs)
        )
        self.max_node_relaunch_times = int(
            os.getenv("DLROVER_TPU_MAX_RELAUNCH",
                      self.max_node_relaunch_times)
        )
        # shard-lease timeout (seconds until an unacked dispatched
        # shard is re-queued); the chaos harness shrinks it so a
        # SIGKILLed agent's leases recover inside the test budget
        try:
            self.seconds_to_timeout_task = float(
                os.getenv("DLROVER_TPU_TASK_TIMEOUT_S",
                          self.seconds_to_timeout_task)
            )
        except ValueError:
            pass
