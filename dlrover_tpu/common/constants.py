"""Every enum / constant used across the framework.

Reference parity: ``dlrover/python/common/constants.py:1-308``.  The TPU
build drops GPU/NPU/PS-specific values and adds TPU-slice concepts
(ICI/DCN, maintenance-event preemption, mesh axis names).
"""


class PlatformType:
    LOCAL = "local"
    KUBERNETES = "k8s"
    RAY = "ray"


class Accelerators:
    TPU = "tpu"
    CPU = "cpu"  # virtual-device CI runs


class NodeType:
    """On TPU there is one training node type (a TPU-VM worker) plus the
    per-job master.  PS/chief/evaluator from the TF lineage are kept as
    names for API parity with PS-style jobs."""

    MASTER = "master"
    WORKER = "worker"
    CHIEF = "chief"
    EVALUATOR = "evaluator"
    PS = "ps"


class NodeStatus:
    INITIAL = "initial"
    PENDING = "pending"
    RUNNING = "running"
    SUCCEEDED = "succeeded"
    FAILED = "failed"
    DELETED = "deleted"
    BREAKDOWN = "breakdown"  # hardware-level failure (chip / host)
    UNKNOWN = "unknown"

    @classmethod
    def end_states(cls):
        return {cls.SUCCEEDED, cls.FAILED, cls.DELETED, cls.BREAKDOWN}


class NodeEventType:
    ADDED = "added"
    MODIFIED = "modified"
    DELETED = "deleted"
    ERROR = "error"


class NodeExitReason:
    SUCCEEDED = "succeeded"
    KILLED = "killed"
    OOM = "oom"
    FATAL_ERROR = "fatal_error"
    HARDWARE_ERROR = "hardware_error"
    PREEMPTED = "preempted"  # TPU maintenance event / spot reclaim
    UNKNOWN_ERROR = "unknown_error"


class JobExitReason:
    SUCCEEDED = "succeeded"
    CODE_ERROR = "code_error"
    WORKER_OOM = "worker_oom"
    WORKER_ERROR = "worker_error"
    PENDING_TIMEOUT = "pending_timeout"
    RDZV_TIMEOUT = "rdzv_timeout"
    HANG_ERROR = "hang_error"
    UNKNOWN_ERROR = "unknown_error"


class DistributionStrategy:
    """Only SPMD (allreduce-family) training exists on TPU; PS is kept
    for API parity."""

    ALLREDUCE = "AllreduceStrategy"
    PS = "ParameterServerStrategy"
    LOCAL = "Local"
    CUSTOM = "CustomStrategy"


class RendezvousName:
    ELASTIC_TRAINING = "elastic-training"
    NETWORK_CHECK = "network-check"


class RendezvousConstant:
    MAX_WAIT_SECS = 600
    PENDING_TIMEOUT = 900


class NetworkFailureReason:
    NO_INIT = "Not initialized"
    NODE_FAILURE = "Node failure"
    WAITING_NODE = "Waiting node"


class TrainingExceptionLevel:
    PROCESS_ERROR = "process_error"
    NODE_ERROR = "node_error"
    RDZV_ERROR = "rdzv_error"
    # the node received a preemption notice / SIGTERM and has DRAINED
    # (fresh shm snapshot flushed): the master should fence it out of
    # the next rendezvous immediately so survivors reshard without
    # waiting for its heartbeat to go stale
    NODE_PREEMPTED = "node_preempted"
    # the master left this node out of the completed comm world
    # (fault / straggler verdict): a scheduling decision, not a crash
    NODE_EXCLUDED = "node_excluded"
    WARNING = "warning"
    INFO = "info"


class AgentExitCode:
    """Distinct agent process exit codes: the supervising controller
    (and the chaos harness) keys recovery policy on WHY the agent
    exited — an excluded node must not be rescheduled into the same
    job the way a generic failure is."""

    SUCCESS = 0
    ERROR = 1
    #: the master excluded this node from the comm world
    NODE_EXCLUDED = 3
    #: the node was preempted and exited after a graceful drain
    NODE_PREEMPTED = 43


class TrainingLoopStatus:
    START = 1
    END = 2
    PENDING = 3


class CheckpointConstant:
    """Flash-checkpoint layout names (reference:
    ``common/constants.py`` ``CheckpointConstant`` +
    ``elastic_agent/torch/ckpt_saver.py`` stage-dir protocol)."""

    CKPT_DIR_PREFIX = "checkpoint-"
    STAGE_DIR = "._dlrover_ckpt_stage"
    STEP_FILE = "latest_step.txt"
    TRACKER_FILE = "latest_checkpointed_iteration.txt"
    STATE_DICT_NAME = "state.msgpack"
    ARRAY_FILE = "arrays.bin"
    METADATA_NAME = "ckpt_meta.json"
    SAVE_TIMEOUT = 600


class SharedMemoryConstant:
    SHM_PREFIX = "dlrover_tpu_shm_"
    LOCK_PREFIX = "dlrover_tpu_lock_"
    QUEUE_PREFIX = "dlrover_tpu_queue_"
    DICT_PREFIX = "dlrover_tpu_dict_"


class NodeEnv:
    """Environment-variable contract between agent and training procs.

    Reference parity: ``common/constants.py`` ``NodeEnv`` (e.g.
    DLROVER_MASTER_ADDR / NODE_RANK); the JAX-specific vars replace the
    torch MASTER_ADDR/MASTER_PORT contract with
    ``jax.distributed.initialize`` coordination.
    """

    MASTER_ADDR = "DLROVER_TPU_MASTER_ADDR"
    JOB_NAME = "DLROVER_TPU_JOB_NAME"
    NODE_ID = "DLROVER_TPU_NODE_ID"
    NODE_RANK = "DLROVER_TPU_NODE_RANK"
    NODE_NUM = "DLROVER_TPU_NODE_NUM"
    NODE_TYPE = "DLROVER_TPU_NODE_TYPE"
    # training-process side
    PROCESS_RANK = "DLROVER_TPU_PROCESS_RANK"
    PROCESS_COUNT = "DLROVER_TPU_PROCESS_COUNT"
    LOCAL_RANK = "DLROVER_TPU_LOCAL_RANK"
    LOCAL_PROCESS_COUNT = "DLROVER_TPU_LOCAL_PROCESS_COUNT"
    COORDINATOR_ADDR = "DLROVER_TPU_COORDINATOR_ADDR"
    RESTART_COUNT = "DLROVER_TPU_RESTART_COUNT"
    # platform
    PLATFORM = "DLROVER_TPU_PLATFORM"
    ACCELERATOR = "DLROVER_TPU_ACCELERATOR"
    DEVICES_PER_PROC = "DLROVER_TPU_DEVICES_PER_PROC"
    GRACE_PERIOD = "DLROVER_TPU_GRACE_PERIOD"
    # testing / fault injection
    FAKE_DEVICE_COUNT = "DLROVER_TPU_FAKE_DEVICE_COUNT"
    MOCK_ERROR_RATE = "DLROVER_TPU_MOCK_ERROR_RATE"
    # monitoring
    MONITOR_INTERVAL = "DLROVER_TPU_MONITOR_INTERVAL"
    CONFIG_DIR = "DLROVER_TPU_CONFIG_DIR"


class ConfigPath:
    """Runtime-tunable config files shared agent<->trainer (reference:
    ``elastic_agent/config/paral_config_tuner.py``)."""

    ENV_PARAL_CONFIG = "DLROVER_TPU_PARAL_CONFIG_PATH"
    PARAL_CONFIG = "/tmp/dlrover_tpu/auto_paral_config.json"
    ENV_RUNTIME_METRICS = "DLROVER_TPU_RUNTIME_METRICS_PATH"
    RUNTIME_METRICS = "/tmp/dlrover_tpu/runtime_metrics.json"


class JobConstant:
    RDZV_JOIN_TIMEOUT_DEFAULT = 600
    MASTER_CLIENT_TIMEOUT = 10
    MASTER_CLIENT_MAX_RETRY = 3
    TRAINING_AGENT_LOOP_INTERVAL = 5
    NODE_HEARTBEAT_INTERVAL = 15
    NODE_HEARTBEAT_TIMEOUT = 120


class GRPC:
    MAX_SEND_MESSAGE_LENGTH = 256 * 1024 * 1024
    MAX_RECEIVE_MESSAGE_LENGTH = 256 * 1024 * 1024
    SERVICE_NAME = "dlrover_tpu.Master"
    REPORT_METHOD = "report"
    GET_METHOD = "get"


class MeshAxis:
    """Canonical named mesh axes for the parallel layer.  Matches the
    reference's parallel-group names (``atorch/distributed/distributed.py``
    ``create_parallel_group`` names "data"/"tensor"/"pipe"/"sequence"/
    "expert") so strategy configs translate 1:1."""

    DATA = "data"
    FSDP = "fsdp"
    TENSOR = "tensor"
    SEQUENCE = "sequence"
    PIPE = "pipe"
    EXPERT = "expert"


class CustomMetricKeys:
    TRAINING_SPEED = "training_speed"
    GLOBAL_STEP = "global_step"
    STEP_TIME = "step_time"


class EventReportConstants:
    TYPE_INFO = "info"
    TYPE_WARN = "warn"
    TYPE_ERROR = "error"
    ACTION_RESTART_TRAIN = "restart_train"
    ACTION_RELAUNCH_NODE = "relaunch_node"
    ACTION_STOP_JOB = "stop_job"
