"""gRPC control-plane transport: two bytes-in/bytes-out unary RPCs.

Reference parity: ``dlrover/proto/elastic_training.proto:26-29`` (the
``Master`` service exposes exactly ``report`` and ``get``) and the channel
helpers in ``dlrover/python/common/grpc.py``.  Instead of protoc codegen
we register the same two methods through grpc's generic handler API with
identity serializers; the payload is the pickled ``Envelope`` from
``dlrover_tpu.common.messages``.
"""

import socket
import time
from concurrent import futures
from typing import Callable, Optional

import grpc

from dlrover_tpu.common.constants import GRPC
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.messages import (
    BoolResponse,
    Envelope,
    Message,
    deserialize_message,
    serialize_message,
)

_CHANNEL_OPTIONS = [
    ("grpc.max_send_message_length", GRPC.MAX_SEND_MESSAGE_LENGTH),
    ("grpc.max_receive_message_length", GRPC.MAX_RECEIVE_MESSAGE_LENGTH),
    ("grpc.enable_retries", 1),
]


def addr_connectable(addr: str, timeout: float = 1.0) -> bool:
    """True if a TCP connect to "host:port" succeeds."""
    if not addr or ":" not in addr:
        return False
    host, _, port = addr.rpartition(":")
    try:
        with socket.create_connection((host, int(port)), timeout=timeout):
            return True
    except (OSError, ValueError):
        return False


def wait_channel_ready(addr: str, timeout: float = 60.0) -> bool:
    """Block until a gRPC channel to ``addr`` is READY (or timeout).

    Replaces the connect-probe polling loop (``addr_connectable`` every
    0.5 s): grpc's own reconnect backoff drives the retries and the
    caller just parks on the ready future — the long-poll shape for
    "wait for the master to come up".
    """
    if not addr or ":" not in addr:
        return False
    channel = grpc.insecure_channel(addr, options=_CHANNEL_OPTIONS)
    try:
        grpc.channel_ready_future(channel).result(timeout=timeout)
        return True
    except grpc.FutureTimeoutError:
        return False
    finally:
        channel.close()


def build_master_server(
    port: int,
    report_fn: Callable[[Envelope], BoolResponse],
    get_fn: Callable[[Envelope], Optional[Message]],
    max_workers: int = 64,
    host: str = "0.0.0.0",
) -> grpc.Server:
    """Create (not start) the master gRPC server.

    ``report_fn``/``get_fn`` receive the deserialized ``Envelope`` and
    return a ``Message`` (or None); transport (de)serialization is
    handled here.
    """

    def _report(request: bytes, _ctx) -> bytes:
        envelope = deserialize_message(request)
        response = report_fn(envelope)
        return serialize_message(response)

    def _get(request: bytes, _ctx) -> bytes:
        envelope = deserialize_message(request)
        response = get_fn(envelope)
        return serialize_message(response)

    handlers = {
        GRPC.REPORT_METHOD: grpc.unary_unary_rpc_method_handler(_report),
        GRPC.GET_METHOD: grpc.unary_unary_rpc_method_handler(_get),
    }
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=_CHANNEL_OPTIONS,
    )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(GRPC.SERVICE_NAME, handlers),)
    )
    server.add_insecure_port(f"{host}:{port}")
    return server


class MasterChannel:
    """Client side of the 2-RPC protocol with retry.

    Reference parity: ``elastic_agent/master_client.py:28`` —
    ``retry_grpc_request``.
    """

    def __init__(
        self,
        addr: str,
        node_id: int = 0,
        node_type: str = "worker",
        timeout: float = 10.0,
        max_retry: int = 3,
    ):
        self._addr = addr
        self._node_id = node_id
        self._node_type = node_type
        self._timeout = timeout
        self._max_retry = max_retry
        #: RPCs actually issued on the wire (attempts, not logical
        #: calls) — what the idle-waiter RPC-bound test and the
        #: control-plane bench count
        self.rpc_count = 0
        self._channel = grpc.insecure_channel(addr, options=_CHANNEL_OPTIONS)
        prefix = f"/{GRPC.SERVICE_NAME}/"
        self._report = self._channel.unary_unary(
            prefix + GRPC.REPORT_METHOD,
            # registered_method is only supported on newer grpcio; skip.
        )
        self._get = self._channel.unary_unary(prefix + GRPC.GET_METHOD)

    @property
    def addr(self) -> str:
        return self._addr

    def close(self):
        self._channel.close()

    def _wrap(self, message: Message) -> bytes:
        return serialize_message(
            Envelope(
                node_id=self._node_id,
                node_type=self._node_type,
                data=serialize_message(message),
            )
        )

    def _call_with_retry(self, rpc, payload: bytes, timeout: float):
        err: Optional[Exception] = None
        for attempt in range(self._max_retry):
            try:
                self.rpc_count += 1
                return rpc(payload, timeout=timeout)
            except grpc.RpcError as e:  # pragma: no cover - network flake
                err = e
                logger.warning(
                    "master rpc to %s failed (attempt %d/%d): %s",
                    self._addr,
                    attempt + 1,
                    self._max_retry,
                    e,
                )
                time.sleep(min(2**attempt, 5))
        raise ConnectionError(f"master at {self._addr} unreachable: {err}")

    def report(self, message: Message, timeout: Optional[float] = None) -> bool:
        raw = self._call_with_retry(
            self._report, self._wrap(message), timeout or self._timeout
        )
        response = deserialize_message(raw)
        return bool(response and response.success)

    def get(self, message: Message, timeout: Optional[float] = None):
        raw = self._call_with_retry(
            self._get, self._wrap(message), timeout or self._timeout
        )
        return deserialize_message(raw)
