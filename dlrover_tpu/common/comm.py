"""gRPC control-plane transport: two bytes-in/bytes-out unary RPCs.

Reference parity: ``dlrover/proto/elastic_training.proto:26-29`` (the
``Master`` service exposes exactly ``report`` and ``get``) and the channel
helpers in ``dlrover/python/common/grpc.py``.  Instead of protoc codegen
we register the same two methods through grpc's generic handler API with
identity serializers; the payload is the pickled ``Envelope`` from
``dlrover_tpu.common.messages``.

Failover semantics (``DLROVER_TPU_MASTER_FAILOVER``, default on):

- retries use JITTERED exponential backoff under a bounded total
  deadline (``DLROVER_TPU_MASTER_RECONNECT_DEADLINE_S``) instead of
  the old fixed-sleep x3 loop, and the channel object is rebuilt after
  repeated failures so a master that came back on the same address is
  re-dialed cleanly;
- every envelope carries the ``(job_epoch, master_incarnation)`` pair
  this client last learned; a ``StaleEpoch`` answer triggers an epoch
  refresh + one transparent re-issue instead of surfacing a crash;
- with the kill-switch off, behavior is today's fail-fast shape:
  ``max_retry`` attempts then ``ConnectionError``, no epochs on the
  wire, ``StaleEpoch`` answers raise.
"""

import random
import socket
import threading
import time
from concurrent import futures
from contextlib import contextmanager
from typing import Callable, Optional

import grpc

from dlrover_tpu.common.constants import GRPC
from dlrover_tpu.common.env import (
    master_failover_enabled,
    master_reconnect_deadline_s,
)
from dlrover_tpu.common.fault_injection import (
    FaultInjectedError,
    get_fault_injector,
)
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.messages import (
    BoolResponse,
    ControlEpochRequest,
    Envelope,
    Message,
    StaleEpoch,
    deserialize_message,
    serialize_message,
)

_CHANNEL_OPTIONS = [
    ("grpc.max_send_message_length", GRPC.MAX_SEND_MESSAGE_LENGTH),
    ("grpc.max_receive_message_length", GRPC.MAX_RECEIVE_MESSAGE_LENGTH),
    ("grpc.enable_retries", 1),
]


class StaleEpochError(ConnectionError):
    """The master kept fencing this client after an epoch refresh —
    its cached job identity is unrecoverably stale."""


def addr_connectable(addr: str, timeout: float = 1.0) -> bool:
    """True if a TCP connect to "host:port" succeeds."""
    if not addr or ":" not in addr:
        return False
    host, _, port = addr.rpartition(":")
    try:
        with socket.create_connection((host, int(port)), timeout=timeout):
            return True
    except (OSError, ValueError):
        return False


def wait_channel_ready(addr: str, timeout: float = 60.0) -> bool:
    """Block until a gRPC channel to ``addr`` is READY (or timeout).

    Replaces the connect-probe polling loop (``addr_connectable`` every
    0.5 s): grpc's own reconnect backoff drives the retries and the
    caller just parks on the ready future — the long-poll shape for
    "wait for the master to come up".
    """
    if not addr or ":" not in addr:
        return False
    channel = grpc.insecure_channel(addr, options=_CHANNEL_OPTIONS)
    try:
        grpc.channel_ready_future(channel).result(timeout=timeout)
        return True
    except grpc.FutureTimeoutError:
        return False
    finally:
        channel.close()


def build_master_server(
    port: int,
    report_fn: Callable[[Envelope], BoolResponse],
    get_fn: Callable[[Envelope], Optional[Message]],
    max_workers: int = 64,
    host: str = "0.0.0.0",
) -> grpc.Server:
    """Create (not start) the master gRPC server.

    ``report_fn``/``get_fn`` receive the deserialized ``Envelope`` and
    return a ``Message`` (or None); transport (de)serialization is
    handled here.
    """

    def _report(request: bytes, _ctx) -> bytes:
        envelope = deserialize_message(request)
        response = report_fn(envelope)
        return serialize_message(response)

    def _get(request: bytes, _ctx) -> bytes:
        envelope = deserialize_message(request)
        response = get_fn(envelope)
        return serialize_message(response)

    handlers = {
        GRPC.REPORT_METHOD: grpc.unary_unary_rpc_method_handler(_report),
        GRPC.GET_METHOD: grpc.unary_unary_rpc_method_handler(_get),
    }
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=_CHANNEL_OPTIONS,
    )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(GRPC.SERVICE_NAME, handlers),)
    )
    server.add_insecure_port(f"{host}:{port}")
    return server


class MasterChannel:
    """Client side of the 2-RPC protocol with retry + reconnection.

    Reference parity: ``elastic_agent/master_client.py:28`` —
    ``retry_grpc_request`` — plus the DLRover property that agents
    simply reattach when the ElasticJob controller recreates a failed
    master pod (PAPER.md §1).
    """

    #: backoff shape: base * 2^(attempt-1), jittered to [0.5, 1.5)x,
    #: capped — a fleet of agents retrying a dead master must not
    #: stampede it in lockstep the moment it returns
    BACKOFF_BASE_S = 0.1
    BACKOFF_CAP_S = 5.0
    #: rebuild the grpc channel after this many consecutive failures
    #: (a replacement master on the same address gets a clean dial)
    RECONNECT_AFTER_FAILURES = 3
    #: bounded transparent re-issues after a StaleEpoch answer
    MAX_EPOCH_REFRESHES = 3

    def __init__(
        self,
        addr: str,
        node_id: int = 0,
        node_type: str = "worker",
        timeout: float = 10.0,
        max_retry: int = 3,
    ):
        self._addr = addr
        self._node_id = node_id
        self._node_type = node_type
        self._timeout = timeout
        self._max_retry = max_retry
        #: RPCs actually issued on the wire (attempts, not logical
        #: calls) — what the idle-waiter RPC-bound test and the
        #: control-plane bench count
        self.rpc_count = 0
        #: wire attempts beyond the first per logical call — the
        #: retry-storm telemetry surfaced as ``control_wait`` retry
        #: spans on the timeline
        self.retry_count = 0
        #: channel rebuilds (master outages survived)
        self.reconnect_count = 0
        #: fencing pair last learned from the master (-1 until a
        #: refresh; -1 is never fenced)
        self.job_epoch = -1
        self.master_incarnation = -1
        #: callback fired when the master's epoch/incarnation pair
        #: CHANGED on refresh — the client invalidates its delta-
        #: protocol caches there
        self.on_epoch_change: Optional[Callable[[int, int], None]] = None
        self._closed = False
        #: per-thread deadline override (``bounded_deadline``): RPCs
        #: issued from inside another call's recovery path inherit a
        #: bounded budget instead of opening their own full deadline
        self._deadline_override = threading.local()
        self._build_channel()

    def _build_channel(self):
        self._channel = grpc.insecure_channel(
            self._addr, options=_CHANNEL_OPTIONS
        )
        prefix = f"/{GRPC.SERVICE_NAME}/"
        self._report = self._channel.unary_unary(
            prefix + GRPC.REPORT_METHOD,
            # registered_method is only supported on newer grpcio; skip.
        )
        self._get = self._channel.unary_unary(prefix + GRPC.GET_METHOD)

    def _reconnect(self):
        """Drop and re-dial the channel (same address — a restarted
        master keeps its port; k8s keeps the service VIP)."""
        self.reconnect_count += 1
        try:
            self._channel.close()
        except Exception:  # noqa: BLE001 - channel already broken
            pass
        self._build_channel()

    @property
    def addr(self) -> str:
        return self._addr

    def close(self):
        #: flags in-flight retry loops (other threads) to abort: a
        #: deliberately-closed channel must not be retried against
        #: until the reconnect deadline
        self._closed = True
        self._channel.close()

    def _wrap(self, message: Message) -> bytes:
        return serialize_message(
            Envelope(
                node_id=self._node_id,
                node_type=self._node_type,
                data=serialize_message(message),
                job_epoch=(
                    self.job_epoch
                    if master_failover_enabled()
                    else -1
                ),
                master_incarnation=(
                    self.master_incarnation
                    if master_failover_enabled()
                    else -1
                ),
            )
        )

    @contextmanager
    def bounded_deadline(self, seconds: float):
        """Cap the retry deadline of every call this THREAD makes
        inside the block (unless the call passes its own
        ``deadline_s``).  Used around the epoch-change re-assertion:
        its RPCs fire from inside another call's retry loop, and each
        opening a fresh full reconnect deadline would block the outer
        caller far past its own."""
        prev = getattr(self._deadline_override, "s", None)
        self._deadline_override.s = seconds
        try:
            yield
        finally:
            self._deadline_override.s = prev

    def _backoff(self, attempt: int, remaining: float) -> float:
        delay = min(
            self.BACKOFF_BASE_S * (2 ** max(attempt - 1, 0)),
            self.BACKOFF_CAP_S,
        )
        delay *= 0.5 + random.random()  # jitter: [0.5, 1.5)x
        return max(min(delay, remaining), 0.0)

    def _call_with_retry(
        self, kind: str, payload: bytes, timeout: float,
        msg_name: str = "",
        deadline_s: Optional[float] = None,
    ):
        """One logical RPC: jittered-exponential retries under a total
        deadline; under failover the channel is also re-dialed after
        repeated failures so a replacement master is picked up.  Each
        retry pause is visible on the timeline as a ``control_wait``
        span with ``kind="retry"`` + a ``retries`` label.

        ``deadline_s`` caps the TOTAL retry budget for this call;
        without it the full reconnect deadline applies.  Nested probes
        (``refresh_epoch`` from inside another call's retry loop) must
        pass it, or the inner loop would run its own full deadline on
        top of the caller's.

        ``kind`` is the logical method ("report" / "get"), resolved to
        the CURRENT stub on every attempt: channels are shared across
        threads, and a concurrent ``_reconnect`` swaps the stubs — a
        captured callable would keep dialing the closed channel for
        the rest of the deadline ("Cannot invoke RPC on closed
        channel!" forever)."""
        failover = master_failover_enabled()
        if deadline_s is None:
            deadline_s = getattr(self._deadline_override, "s", None)
        if deadline_s is None:
            deadline_s = (
                master_reconnect_deadline_s() if failover else 60.0
            )
        deadline = time.monotonic() + deadline_s
        injector = get_fault_injector()
        err: Optional[Exception] = None
        attempt = 0
        while True:
            attempt += 1
            try:
                if self._closed:
                    raise ConnectionError(
                        f"channel to {self._addr} closed locally"
                    )
                rpc = (
                    self._report if kind == "report" else self._get
                )
                action = ""
                if injector is not None:
                    action = injector.on_rpc(msg_name)
                self.rpc_count += 1
                if action == "dup":
                    # duplicate delivery: the extra send exercises the
                    # master's idempotency; the caller consumes the
                    # second (authoritative) answer
                    self.rpc_count += 1
                    rpc(payload, timeout=timeout)
                raw = rpc(payload, timeout=timeout)
                if (
                    attempt > 1
                    and failover
                    and msg_name != "ControlEpochRequest"
                ):
                    # the call came back after failures: the master
                    # may be a NEW incarnation (or job epoch) — learn
                    # the fencing pair so delta caches invalidate and
                    # subsequent RPCs fence correctly
                    try:
                        self.refresh_epoch(deadline_s=10.0)
                    except ConnectionError:
                        pass  # it flapped; the answer still stands
                return raw
            except (
                grpc.RpcError,
                FaultInjectedError,
                ValueError,  # "Cannot invoke RPC on closed channel!"
            ) as e:
                err = e
                logger.warning(
                    "master rpc to %s failed (attempt %d): %s",
                    self._addr, attempt, e,
                )
                if not failover:
                    # kill-switched: today's fixed sleep schedule
                    # EXACTLY (1 s, 2 s, 4 s … cap 5 s, after every
                    # failure including the last) — the legacy path
                    # tolerated a multi-second master stall between
                    # attempts, and shrinking that window would turn
                    # survivable flakes into job crashes
                    delay = min(2.0 ** (attempt - 1), 5.0)
                    t0_mono = time.monotonic()
                    time.sleep(delay)
                    if attempt >= self._max_retry:
                        break
                    self.retry_count += 1
                    self._emit_retry_span(t0_mono, delay, attempt)
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                delay = self._backoff(attempt, remaining)
                self.retry_count += 1
                t0_mono = time.monotonic()
                time.sleep(delay)
                self._emit_retry_span(t0_mono, delay, attempt)
                if (
                    failover
                    and attempt % self.RECONNECT_AFTER_FAILURES == 0
                ):
                    # rebuild swaps self._report/self._get for stubs
                    # on the NEW channel; every attempt re-resolves
                    # from ``kind`` so all threads pick them up
                    self._reconnect()
                if failover and msg_name != "ControlEpochRequest":
                    # probe the epoch BEFORE re-issuing: a parked
                    # long-poll re-sent to a restarted master would
                    # otherwise park its whole chunk before the
                    # client learns the incarnation changed and
                    # re-asserts linger-window-lost state (joins, kv
                    # sets) via on_epoch_change.  The probe is ONE
                    # quick attempt (deadline_s caps its own retry
                    # loop) — the OUTER deadline owns the waiting.
                    try:
                        self.refresh_epoch(timeout=2.0, deadline_s=2.0)
                    except ConnectionError:
                        pass  # still down; keep backing off
        raise ConnectionError(f"master at {self._addr} unreachable: {err}")

    def _emit_retry_span(self, t0_mono: float, delay: float, attempt: int):
        from dlrover_tpu.observability.events import (
            anchored_now,
            get_event_logger,
        )

        # after-the-fact complete(): the start must come off the
        # anchored clock or an NTP step during a retry storm puts
        # these X-spans on a different timeline than B/E spans
        get_event_logger().complete(
            "control_wait", anchored_now(t0_mono), delay,
            kind="retry", retries=attempt,
        )

    def refresh_epoch(
        self, timeout: float = 5.0,
        deadline_s: Optional[float] = None,
    ) -> bool:
        """Learn the master's current ``(job_epoch, incarnation)``.
        Returns True when the pair CHANGED (caches must be dropped).
        ``deadline_s`` bounds the total retry budget — callers probing
        from inside another deadline must pass it."""
        raw = self._call_with_retry(
            "get",
            self._wrap(ControlEpochRequest()),
            timeout,
            msg_name="ControlEpochRequest",
            deadline_s=deadline_s,
        )
        response = deserialize_message(raw)
        epoch = getattr(response, "job_epoch", None)
        inc = getattr(response, "incarnation", None)
        if epoch is None or inc is None:
            return False
        changed = (
            epoch != self.job_epoch or inc != self.master_incarnation
        )
        self.job_epoch, self.master_incarnation = epoch, inc
        if changed and self.on_epoch_change is not None:
            try:
                self.on_epoch_change(epoch, inc)
            except Exception as e:  # noqa: BLE001
                logger.warning("epoch-change callback failed: %s", e)
        return changed

    def _adopt(self, stale: StaleEpoch):
        changed = (
            stale.job_epoch != self.job_epoch
            or stale.incarnation != self.master_incarnation
        )
        self.job_epoch = stale.job_epoch
        self.master_incarnation = stale.incarnation
        if changed and self.on_epoch_change is not None:
            try:
                self.on_epoch_change(stale.job_epoch, stale.incarnation)
            except Exception as e:  # noqa: BLE001
                logger.warning("epoch-change callback failed: %s", e)

    def _roundtrip(self, kind: str, message: Message, timeout: float):
        """Serialize, send with retry, deserialize — with transparent
        StaleEpoch refresh+re-issue under failover."""
        name = type(message).__name__
        for _ in range(self.MAX_EPOCH_REFRESHES):
            raw = self._call_with_retry(
                kind, self._wrap(message), timeout, msg_name=name
            )
            response = deserialize_message(raw)
            if not isinstance(response, StaleEpoch):
                return response
            if not master_failover_enabled():
                raise StaleEpochError(
                    f"master fenced {name}: job_epoch="
                    f"{response.job_epoch}"
                )
            self._adopt(response)
        raise StaleEpochError(
            f"master kept fencing {name} after "
            f"{self.MAX_EPOCH_REFRESHES} epoch refreshes"
        )

    def report(self, message: Message, timeout: Optional[float] = None) -> bool:
        response = self._roundtrip(
            "report", message, timeout or self._timeout
        )
        return bool(response and getattr(response, "success", False))

    def get(self, message: Message, timeout: Optional[float] = None):
        return self._roundtrip(
            "get", message, timeout or self._timeout
        )
