"""Checkpoint storage abstraction.

Reference parity: ``dlrover/python/common/storage.py:24,128,203,231,258``
(CheckpointStorage ABC, PosixDiskStorage, deletion strategies).  A GCS
backend slot exists for TPU deployments (gated: the bare image has no
``google-cloud-storage``; POSIX paths cover GCS-Fuse mounts, the common
TPU-VM setup).
"""

import json
import os
import shutil
from abc import ABCMeta, abstractmethod
from typing import Callable, List, Optional

from dlrover_tpu.common.log import default_logger as logger


class CheckpointDeletionStrategy(metaclass=ABCMeta):
    @abstractmethod
    def clean_up(self, step: int, delete_func: Callable[[str], None]):
        """Decide which old checkpoint dirs to delete after ``step`` was
        persisted; call ``delete_func(path)`` for each victim."""


class KeepStepIntervalStrategy(CheckpointDeletionStrategy):
    """Keep only checkpoints whose step % keep_interval == 0."""

    def __init__(self, keep_interval: int, checkpoint_dir: str):
        self._keep_interval = keep_interval
        self._checkpoint_dir = checkpoint_dir

    def clean_up(self, step: int, delete_func):
        if step % self._keep_interval == 0:
            return
        path = os.path.join(self._checkpoint_dir, f"checkpoint-{step}")
        try:
            delete_func(path)
        except Exception as e:  # noqa: BLE001
            logger.warning("fail to clean up %s: %s", path, e)


class KeepLatestStepStrategy(CheckpointDeletionStrategy):
    """Keep at most ``max_to_keep`` newest checkpoints."""

    def __init__(self, max_to_keep: int, checkpoint_dir: str):
        self._max_to_keep = max(max_to_keep, 1)
        self._checkpoint_dir = checkpoint_dir
        self._steps: List[int] = []

    def clean_up(self, step: int, delete_func):
        self._steps.append(step)
        while len(self._steps) > self._max_to_keep:
            victim = self._steps.pop(0)
            path = os.path.join(self._checkpoint_dir, f"checkpoint-{victim}")
            try:
                delete_func(path)
            except Exception as e:  # noqa: BLE001
                logger.warning("fail to clean up %s: %s", path, e)


class CheckpointStorage(metaclass=ABCMeta):
    """Byte/file-level IO used by the async saver and the load path."""

    @abstractmethod
    def write(self, content, path: str):
        ...

    def write_chunks(self, chunks, path: str):
        """Write a sequence of byte-like chunks as one file. Default
        joins in memory; byte-addressable backends should stream."""
        self.write(b"".join(bytes(c) for c in chunks), path)

    @abstractmethod
    def read(self, path: str, mode: str = "r"):
        ...

    @abstractmethod
    def safe_rmtree(self, dir_path: str):
        ...

    @abstractmethod
    def safe_remove(self, path: str):
        ...

    @abstractmethod
    def safe_makedirs(self, dir_path: str):
        ...

    @abstractmethod
    def safe_move(self, src: str, dst: str):
        ...

    @abstractmethod
    def exists(self, path: str) -> bool:
        ...

    @abstractmethod
    def listdir(self, path: str) -> List[str]:
        ...

    def write_json(self, obj, path: str):
        self.write(json.dumps(obj), path)

    def read_json(self, path: str) -> Optional[dict]:
        content = self.read(path)
        if not content:
            return None
        try:
            return json.loads(content)
        except json.JSONDecodeError:
            return None


class PosixDiskStorage(CheckpointStorage):
    def write(self, content, path: str):
        mode = "wb" if isinstance(content, (bytes, bytearray, memoryview)) else "w"
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, mode) as f:
            f.write(content)
            f.flush()
            os.fsync(f.fileno())

    def write_chunks(self, chunks, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "wb") as f:
            for chunk in chunks:
                f.write(chunk)
            f.flush()
            os.fsync(f.fileno())

    def read(self, path: str, mode: str = "r"):
        if not os.path.exists(path):
            return "" if "b" not in mode else b""
        with open(path, mode) as f:
            return f.read()

    def safe_rmtree(self, dir_path: str):
        shutil.rmtree(dir_path, ignore_errors=True)

    def safe_remove(self, path: str):
        if os.path.exists(path):
            os.remove(path)

    def safe_makedirs(self, dir_path: str):
        os.makedirs(dir_path, exist_ok=True)

    def safe_move(self, src: str, dst: str):
        if os.path.exists(src) and not os.path.exists(dst):
            shutil.move(src, dst)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def listdir(self, path: str) -> List[str]:
        if not os.path.isdir(path):
            return []
        return sorted(os.listdir(path))


class PosixStorageWithDeletion(PosixDiskStorage):
    """POSIX storage that applies a deletion strategy after each commit
    of a persisted step (reference: ``common/storage.py:258``)."""

    def __init__(self, tracker_file: str, deletion_strategy):
        super().__init__()
        self._tracker_file = tracker_file
        self._deletion_strategy = deletion_strategy

    def write(self, content, path: str):
        # committing the tracker file marks a persisted step
        if os.path.basename(path) == os.path.basename(self._tracker_file):
            try:
                prev = self.read(path)
                if prev:
                    self._deletion_strategy.clean_up(
                        int(prev), self.safe_rmtree
                    )
            except (ValueError, OSError) as e:
                logger.warning("deletion strategy failed: %s", e)
        super().write(content, path)


def get_checkpoint_storage(
    deletion_strategy=None, tracker_file: str = ""
) -> CheckpointStorage:
    if deletion_strategy:
        return PosixStorageWithDeletion(tracker_file, deletion_strategy)
    return PosixDiskStorage()
