"""Checkpoint storage abstraction.

Reference parity: ``dlrover/python/common/storage.py:24,128,203,231,258``
(CheckpointStorage ABC, PosixDiskStorage, deletion strategies), extended
with an fsspec-backed object-store tier (``FsspecStorage``): on a TPU
pod the VM-local disk dies with the VM, so the persistence story IS the
object store (SURVEY §5.4 "agent-side async persist to GCS").  Any
fsspec URL works — ``gs://`` (gcsfs), ``s3://``, ``memory://`` (tests)
— selected automatically by :func:`get_checkpoint_storage` from the
checkpoint path's protocol.
"""

import json
import os
import shutil
from abc import ABCMeta, abstractmethod
from typing import Callable, List, Optional

from dlrover_tpu.common.log import default_logger as logger


class CheckpointDeletionStrategy(metaclass=ABCMeta):
    @abstractmethod
    def clean_up(self, step: int, delete_func: Callable[[str], None]):
        """Decide which old checkpoint dirs to delete after ``step`` was
        persisted; call ``delete_func(path)`` for each victim."""


class KeepStepIntervalStrategy(CheckpointDeletionStrategy):
    """Keep only checkpoints whose step % keep_interval == 0."""

    def __init__(self, keep_interval: int, checkpoint_dir: str):
        self._keep_interval = keep_interval
        self._checkpoint_dir = checkpoint_dir

    def clean_up(self, step: int, delete_func):
        if step % self._keep_interval == 0:
            return
        path = os.path.join(self._checkpoint_dir, f"checkpoint-{step}")
        try:
            delete_func(path)
        except Exception as e:  # noqa: BLE001
            logger.warning("fail to clean up %s: %s", path, e)


class KeepLatestStepStrategy(CheckpointDeletionStrategy):
    """Keep at most ``max_to_keep`` newest checkpoints."""

    def __init__(self, max_to_keep: int, checkpoint_dir: str):
        self._max_to_keep = max(max_to_keep, 1)
        self._checkpoint_dir = checkpoint_dir
        self._steps: List[int] = []

    def clean_up(self, step: int, delete_func):
        self._steps.append(step)
        while len(self._steps) > self._max_to_keep:
            victim = self._steps.pop(0)
            path = os.path.join(self._checkpoint_dir, f"checkpoint-{victim}")
            try:
                delete_func(path)
            except Exception as e:  # noqa: BLE001
                logger.warning("fail to clean up %s: %s", path, e)


class CheckpointStorage(metaclass=ABCMeta):
    """Byte/file-level IO used by the async saver and the load path."""

    @abstractmethod
    def write(self, content, path: str):
        ...

    def write_chunks(self, chunks, path: str):
        """Write an iterable of byte-like chunks as one file. Default
        joins in memory; byte-addressable backends should stream."""
        self.write(b"".join(bytes(c) for c in chunks), path)

    def open_read(self, path: str):
        """A binary file-like handle for streaming reads (the restore
        path fills a preallocated buffer chunk by chunk instead of
        materializing the whole object).  Default buffers the full
        read; real backends override with a true stream.  Raises
        FileNotFoundError on absence."""
        import io

        data = self.read(path, "rb")
        if not data and not self.exists(path):
            raise FileNotFoundError(path)
        return io.BytesIO(data)

    @abstractmethod
    def read(self, path: str, mode: str = "r"):
        ...

    @abstractmethod
    def safe_rmtree(self, dir_path: str):
        ...

    @abstractmethod
    def safe_remove(self, path: str):
        ...

    @abstractmethod
    def safe_makedirs(self, dir_path: str):
        ...

    @abstractmethod
    def safe_move(self, src: str, dst: str):
        ...

    @abstractmethod
    def exists(self, path: str) -> bool:
        ...

    @abstractmethod
    def listdir(self, path: str) -> List[str]:
        ...

    def write_json(self, obj, path: str):
        self.write(json.dumps(obj), path)

    def read_json(self, path: str) -> Optional[dict]:
        content = self.read(path)
        if not content:
            return None
        try:
            return json.loads(content)
        except json.JSONDecodeError:
            return None


class PosixDiskStorage(CheckpointStorage):
    def write(self, content, path: str):
        mode = "wb" if isinstance(content, (bytes, bytearray, memoryview)) else "w"
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, mode) as f:
            f.write(content)
            f.flush()
            os.fsync(f.fileno())

    def write_chunks(self, chunks, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "wb") as f:
            for chunk in chunks:
                f.write(chunk)
            f.flush()
            os.fsync(f.fileno())

    def read(self, path: str, mode: str = "r"):
        if not os.path.exists(path):
            return "" if "b" not in mode else b""
        with open(path, mode) as f:
            return f.read()

    def open_read(self, path: str):
        return open(path, "rb")

    def safe_rmtree(self, dir_path: str):
        shutil.rmtree(dir_path, ignore_errors=True)

    def safe_remove(self, path: str):
        if os.path.exists(path):
            os.remove(path)

    def safe_makedirs(self, dir_path: str):
        os.makedirs(dir_path, exist_ok=True)

    def safe_move(self, src: str, dst: str):
        if os.path.exists(src) and not os.path.exists(dst):
            shutil.move(src, dst)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def listdir(self, path: str) -> List[str]:
        if not os.path.isdir(path):
            return []
        return sorted(os.listdir(path))


class FsspecStorage(CheckpointStorage):
    """Object-store checkpoint IO over any fsspec filesystem.

    Commit semantics differ from POSIX: object stores have no atomic
    directory rename, so ``safe_move`` is server-side copy+delete per
    object (non-atomic).  The saver's protocol stays crash-consistent
    anyway because the single-object tracker-file write — which IS
    atomic on GCS/S3 — is the commit point: a reader follows the
    tracker to a fully-populated final dir or ignores the orphaned
    stage prefix.

    ``write_chunks`` streams each chunk straight into the backend's
    buffered upload (multipart on GCS/S3) — a shard-sized shm shard is
    never materialized host-side a second time.
    """

    def __init__(self, protocol_or_url: str, fs=None, **fs_kwargs):
        import fsspec

        if fs is not None:
            self._fs = fs
        else:
            protocol = protocol_or_url.split("://", 1)[0]
            self._fs = fsspec.filesystem(protocol, **fs_kwargs)

    def _p(self, path: str) -> str:
        return self._fs._strip_protocol(path)

    def write(self, content, path: str):
        if isinstance(content, str):
            content = content.encode()
        p = self._p(path)
        with self._fs.open(p, "wb") as f:
            f.write(bytes(content))

    def write_chunks(self, chunks, path: str):
        with self._fs.open(self._p(path), "wb") as f:
            for chunk in chunks:
                f.write(bytes(chunk))

    def open_read(self, path: str):
        # a true stream: fsspec buffers block-sized reads, so the
        # restore path never holds shard-sized bytes besides its own
        # destination buffer
        return self._fs.open(self._p(path), "rb")

    def read(self, path: str, mode: str = "r"):
        p = self._p(path)
        try:
            data = self._fs.cat_file(p)
        except (FileNotFoundError, IsADirectoryError):
            # ONLY genuine absence maps to empty — a transient network
            # error (TimeoutError etc. are OSError subclasses) must
            # raise, or a flaky tracker read would silently restart
            # training from step 0 with checkpoints in the bucket
            return b"" if "b" in mode else ""
        return data if "b" in mode else data.decode()

    def safe_rmtree(self, dir_path: str):
        p = self._p(dir_path)
        try:
            self._fs.rm(p, recursive=True)
        except (FileNotFoundError, OSError):
            pass

    def safe_remove(self, path: str):
        p = self._p(path)
        try:
            self._fs.rm_file(p)
        except (FileNotFoundError, OSError):
            pass

    def safe_makedirs(self, dir_path: str):
        # prefixes need no creation on object stores; makedirs keeps
        # directory-full filesystems (memory://, local) working
        try:
            self._fs.makedirs(self._p(dir_path), exist_ok=True)
        except (OSError, ValueError):
            pass

    def safe_move(self, src: str, dst: str):
        s, d = self._p(src), self._p(dst)
        if not self._fs.exists(s) or self._fs.exists(d):
            return
        self._fs.mv(s, d, recursive=True)

    def exists(self, path: str) -> bool:
        return bool(self._fs.exists(self._p(path)))

    def listdir(self, path: str) -> List[str]:
        p = self._p(path)
        try:
            # bust the dircache: node-0's commit loop polls for done
            # files OTHER nodes write; a cached listing would never
            # show them and every multi-node commit would time out
            self._fs.invalidate_cache(p)
            entries = self._fs.ls(p, detail=False)
        except (FileNotFoundError, OSError):
            return []
        # ls returns full paths (files AND sub-prefixes); callers want
        # names, like os.listdir
        return sorted(
            e.rstrip("/").rsplit("/", 1)[-1]
            for e in entries
            if e.rstrip("/") != p.rstrip("/")
        )


class StorageWithDeletion(CheckpointStorage):
    """Wrap any storage with a deletion strategy applied after each
    tracker-file commit (reference: ``common/storage.py:258``).
    Composition, so the POSIX and fsspec tiers share it."""

    def __init__(self, base: CheckpointStorage, tracker_file: str,
                 deletion_strategy):
        self._base = base
        self._tracker_file = tracker_file
        self._deletion_strategy = deletion_strategy

    def write(self, content, path: str):
        # committing the tracker file marks a persisted step
        if os.path.basename(path) == os.path.basename(self._tracker_file):
            try:
                prev = self._base.read(path)
                if prev:
                    self._deletion_strategy.clean_up(
                        int(prev), self._base.safe_rmtree
                    )
            except (ValueError, OSError) as e:
                logger.warning("deletion strategy failed: %s", e)
        self._base.write(content, path)

    def write_chunks(self, chunks, path: str):
        self._base.write_chunks(chunks, path)

    def read(self, path: str, mode: str = "r"):
        return self._base.read(path, mode)

    def open_read(self, path: str):
        return self._base.open_read(path)

    def safe_rmtree(self, dir_path: str):
        self._base.safe_rmtree(dir_path)

    def safe_remove(self, path: str):
        self._base.safe_remove(path)

    def safe_makedirs(self, dir_path: str):
        self._base.safe_makedirs(dir_path)

    def safe_move(self, src: str, dst: str):
        self._base.safe_move(src, dst)

    def exists(self, path: str) -> bool:
        return self._base.exists(path)

    def listdir(self, path: str) -> List[str]:
        return self._base.listdir(path)


class PosixStorageWithDeletion(StorageWithDeletion):
    """Back-compat alias: POSIX storage + deletion strategy."""

    def __init__(self, tracker_file: str, deletion_strategy):
        super().__init__(
            PosixDiskStorage(), tracker_file, deletion_strategy
        )


def is_remote_url(path: Optional[str]) -> bool:
    """True when ``path`` carries an fsspec protocol.  file:// counts:
    PosixDiskStorage would treat the URL as a cwd-relative literal
    path; fsspec's LocalFileSystem strips the scheme and resolves it
    correctly.  The single source of truth for every call site that
    branches on URL-ness (storage selection, makedirs skip, shm
    namespace hashing)."""
    return bool(path and "://" in path)


_is_remote_url = is_remote_url  # back-compat private alias


def get_checkpoint_storage(
    deletion_strategy=None, tracker_file: str = "",
    path: Optional[str] = None,
) -> CheckpointStorage:
    """Storage for ``path``: fsspec when it carries an object-store
    protocol (``gs://…``, ``s3://…``, ``memory://…``), POSIX disk
    otherwise; optionally wrapped with a deletion strategy."""
    if is_remote_url(path):
        base: CheckpointStorage = FsspecStorage(path)
    else:
        base = PosixDiskStorage()
    if deletion_strategy:
        return StorageWithDeletion(
            base, tracker_file, deletion_strategy
        )
    return base
