"""Seeded, env-driven fault injection for chaos testing.

The plan rides ``DLROVER_TPU_FAULT_PLAN`` (a JSON object, see
:class:`FaultPlan`) into every process of a job; each process also
declares its role via ``DLROVER_TPU_FAULT_ROLE`` (``master`` /
``agent`` / anything — the orchestrator in ``scripts/chaos.py`` sets
it on the children it spawns).  Two fault families:

- ``kill`` — SIGKILL the current process when execution reaches a
  named phase hook (:func:`maybe_crash` call sites: mid_rendezvous,
  mid_long_poll, mid_report_flush, mid_checkpoint_persist,
  mid_weight_publish) and the
  spec's role/occurrence filters match.  This is how "the master dies
  mid-rendezvous" is reproduced deterministically instead of by
  racing a timer against the serve loop.
- ``rpc`` — drop / delay / duplicate individual RPCs at the
  ``MasterChannel`` boundary (:meth:`FaultInjector.on_rpc`), matched
  by request class name, with a seeded probability.

Every injected fault emits a ``fault_injected`` instant event
(labels: ``kind`` + ``target``, schema-enforced) on the PR-1 timeline
before it acts, so chaos runs are attributable in the same trace as
the recovery they provoke.

With no plan configured every hook is a cheap no-op (one module-level
``None`` check) — production code paths pay nothing.
"""

import json
import os
import random
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional

from dlrover_tpu.common.log import default_logger as logger

FAULT_PLAN_ENV = "DLROVER_TPU_FAULT_PLAN"
FAULT_ROLE_ENV = "DLROVER_TPU_FAULT_ROLE"

#: the closed phase-hook vocabulary (``maybe_crash`` call sites)
KILL_PHASES = (
    "mid_rendezvous",
    "mid_long_poll",
    "mid_report_flush",
    "mid_checkpoint_persist",
    "mid_weight_publish",
)


class FaultInjectedError(ConnectionError):
    """A dropped RPC, surfaced as the transport failure it simulates."""


@dataclass
class FaultSpec:
    """One fault in a plan.

    ``kind``: ``kill`` | ``rpc``.
    ``target``: role filter (``master`` / ``agent`` / "" = any) for
    kills; for rpc faults the request CLASS NAME to match ("" or
    ``*`` = any RPC).
    ``phase``: kill hook name (one of :data:`KILL_PHASES`).
    ``op``: rpc fault operation — ``drop`` | ``delay`` | ``dup``.
    ``after``: skip the first N matching occurrences before arming.
    ``count``: fire at most N times (-1 = unlimited).
    ``prob``: seeded per-occurrence probability once armed.
    ``delay_s``: sleep for ``op=delay``.
    """

    kind: str = "rpc"
    target: str = ""
    phase: str = ""
    op: str = "drop"
    after: int = 0
    count: int = 1
    prob: float = 1.0
    delay_s: float = 0.0
    # runtime occurrence bookkeeping (not part of the plan)
    seen: int = 0
    fired: int = 0


@dataclass
class FaultPlan:
    seed: int = 0
    faults: List[FaultSpec] = field(default_factory=list)

    @classmethod
    def from_json(cls, raw: str) -> "FaultPlan":
        data = json.loads(raw)
        specs = []
        for f in data.get("faults", []):
            known = {
                k: v
                for k, v in f.items()
                if k in FaultSpec.__dataclass_fields__
            }
            spec = FaultSpec(**known)
            if spec.kind == "kill" and spec.phase not in KILL_PHASES:
                raise ValueError(
                    f"unknown kill phase {spec.phase!r} "
                    f"(declared: {KILL_PHASES})"
                )
            specs.append(spec)
        return cls(seed=int(data.get("seed", 0)), faults=specs)

    @classmethod
    def from_env(cls) -> Optional["FaultPlan"]:
        raw = os.getenv(FAULT_PLAN_ENV, "")
        if not raw:
            return None
        try:
            return cls.from_json(raw)
        except (ValueError, TypeError) as e:
            logger.warning("ignoring malformed %s: %s",
                           FAULT_PLAN_ENV, e)
            return None


class FaultInjector:
    """Evaluates a :class:`FaultPlan` at the instrumented hooks."""

    def __init__(self, plan: FaultPlan, role: str = ""):
        self._plan = plan
        self._role = role or os.getenv(FAULT_ROLE_ENV, "")
        self._rng = random.Random(plan.seed)
        self._lock = threading.Lock()

    @property
    def role(self) -> str:
        return self._role

    def _armed(self, spec: FaultSpec) -> bool:
        """Caller holds the lock: occurrence bookkeeping + seeded
        probability for one matching occurrence."""
        spec.seen += 1
        if spec.seen <= spec.after:
            return False
        if spec.count >= 0 and spec.fired >= spec.count:
            return False
        if spec.prob < 1.0 and self._rng.random() >= spec.prob:
            return False
        spec.fired += 1
        return True

    def _emit(self, kind: str, target: str, **labels):
        from dlrover_tpu.observability.events import get_event_logger

        get_event_logger().instant(
            "fault_injected", kind=kind, target=target, **labels
        )

    # ------------------------------------------------------- kill hooks
    def maybe_crash(self, phase: str):
        """SIGKILL the current process when a kill spec matches this
        phase + role.  The ``fault_injected`` marker is written first
        (O_APPEND, synchronous) so the timeline records the cause."""
        for spec in self._plan.faults:
            if spec.kind != "kill" or spec.phase != phase:
                continue
            if spec.target and spec.target != self._role:
                continue
            with self._lock:
                if not self._armed(spec):
                    continue
            logger.warning(
                "fault plan: SIGKILL self (%s) at %s",
                self._role or "?", phase,
            )
            self._emit("kill", self._role or "self", phase=phase)
            os.kill(os.getpid(), signal.SIGKILL)
            time.sleep(60)  # pragma: no cover - the signal lands first

    # -------------------------------------------------------- rpc hooks
    def on_rpc(self, msg_name: str) -> str:
        """Consulted by ``MasterChannel`` before each wire attempt.

        Returns ``"dup"`` when the RPC should be sent twice, ``""``
        otherwise; raises :class:`FaultInjectedError` for a drop;
        sleeps in place for a delay."""
        for spec in self._plan.faults:
            if spec.kind != "rpc":
                continue
            if spec.target not in ("", "*", msg_name):
                continue
            with self._lock:
                if not self._armed(spec):
                    continue
            self._emit("rpc_" + spec.op, msg_name,
                       delay_s=spec.delay_s)
            if spec.op == "drop":
                raise FaultInjectedError(
                    f"fault plan dropped rpc {msg_name}"
                )
            if spec.op == "delay":
                time.sleep(max(spec.delay_s, 0.0))
            elif spec.op == "dup":
                return "dup"
        return ""


_injector: Optional[FaultInjector] = None
_injector_loaded = False
_injector_lock = threading.Lock()


def get_fault_injector() -> Optional[FaultInjector]:
    """Process-wide injector, built lazily from the env; None (and
    near-free) when no plan is configured."""
    global _injector, _injector_loaded
    if _injector_loaded:
        return _injector
    with _injector_lock:
        if not _injector_loaded:
            plan = FaultPlan.from_env()
            _injector = (
                FaultInjector(plan) if plan is not None else None
            )
            _injector_loaded = True
    return _injector


def reset_fault_injector():
    """Drop the cached injector so the next call re-reads the env
    (tests and harnesses that flip the plan mid-process)."""
    global _injector, _injector_loaded
    with _injector_lock:
        _injector = None
        _injector_loaded = False


def maybe_crash(phase: str):
    """Module-level kill hook — safe to call unconditionally from any
    instrumented site."""
    injector = get_fault_injector()
    if injector is not None:
        injector.maybe_crash(phase)
