"""Chunked parallel-copy substrate for the flash-checkpoint data plane.

Every checkpoint hot path is, at bottom, a large host-side memcpy:
draining device snapshots into shared memory (``ckpt_shm.save_state``),
rebuilding private buffers on restore (``load_state(copy=True)``),
faulting in freshly-created segments (``preallocate``) and streaming
shm out to storage (``dump_to_file``).  A single-threaded NumPy copy
tops out at one core's bandwidth — and when the destination pages are
cold, at the first-touch fault rate (measured 0.17 GB/s faulting vs
7.7 GB/s resident in the build container).  NumPy copies on DISJOINT
slices release the GIL, so N worker threads give ~N× effective
bandwidth up to the memory bus; the same chunking bounds peak extra
memory on streaming writes.  This is the shape of fix CheckFreq's
pipelined snapshot/persist split and Gemini's chunked in-memory
traffic scheduling use for the same problem.

Tunables (environment):

- ``DLROVER_TPU_CKPT_COPY_WORKERS``: copy thread count.  ``1`` is the
  byte-identical serial fallback — no pool, no background threads, the
  exact pre-parallel code path.  Default: ``min(cpu_count, 8)``.
- ``DLROVER_TPU_CKPT_CHUNK_MB``: chunk granularity for both parallel
  copies and streaming writes.  Default 64 MB.

The worker pool is process-wide, lazily created, and fork-aware (a
forked child never inherits dead executor threads).
"""

import os
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Iterator, Optional, Tuple

import numpy as np

COPY_WORKERS_ENV = "DLROVER_TPU_CKPT_COPY_WORKERS"
CHUNK_MB_ENV = "DLROVER_TPU_CKPT_CHUNK_MB"
#: input-plane override; falls back to the ckpt worker count so one
#: knob tunes the whole host data plane unless the input ring needs
#: its own setting (e.g. leave cores for preprocessing workers)
INPUT_COPY_WORKERS_ENV = "DLROVER_TPU_INPUT_COPY_WORKERS"

_DEFAULT_CHUNK_MB = 64
#: below this, thread dispatch costs more than the copy saves
MIN_PARALLEL_BYTES = 8 * 1024 * 1024


def copy_workers() -> int:
    """Configured copy-thread count (>= 1)."""
    raw = os.getenv(COPY_WORKERS_ENV, "")
    if raw:
        try:
            return max(1, int(raw))
        except ValueError:
            pass
    return max(1, min(os.cpu_count() or 1, 8))


def input_copy_workers() -> int:
    """Copy-thread count for the input data plane (shm batch ring,
    pipelined loader).  ``DLROVER_TPU_INPUT_COPY_WORKERS`` when set,
    else the checkpoint worker count — ``1`` remains the byte-identical
    serial fallback for both planes."""
    raw = os.getenv(INPUT_COPY_WORKERS_ENV, "")
    if raw:
        try:
            return max(1, int(raw))
        except ValueError:
            pass
    return copy_workers()


def chunk_nbytes() -> int:
    """Configured chunk size in bytes (>= 1 MB)."""
    raw = os.getenv(CHUNK_MB_ENV, "")
    try:
        mb = int(raw) if raw else _DEFAULT_CHUNK_MB
    except ValueError:
        mb = _DEFAULT_CHUNK_MB
    return max(1, mb) * 1024 * 1024


def chunked_iter(total: int,
                 chunk: Optional[int] = None) -> Iterator[Tuple[int, int]]:
    """Yield ``(offset, length)`` covering ``[0, total)`` in order."""
    chunk = chunk or chunk_nbytes()
    off = 0
    while off < total:
        n = min(chunk, total - off)
        yield off, n
        off += n


_pool: Optional[ThreadPoolExecutor] = None
_pool_workers = 0
_pool_pid = -1
_pool_lock = threading.Lock()


def _get_pool(workers: int) -> ThreadPoolExecutor:
    global _pool, _pool_workers, _pool_pid
    with _pool_lock:
        if (
            _pool is None
            or _pool_workers < workers
            or _pool_pid != os.getpid()  # forked child: threads are gone
        ):
            if _pool is not None and _pool_pid == os.getpid():
                _pool.shutdown(wait=False)
            _pool = ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="ckpt-io"
            )
            _pool_workers = workers
            _pool_pid = os.getpid()
        return _pool


def submit(fn, *args, **kwargs) -> Future:
    """Run ``fn`` on the shared pool (for pipeline stages like the
    drain's leaf materialization).  With workers=1 the pool still has
    one thread, so a single in-flight prefetch stays legal."""
    return _get_pool(max(copy_workers(), 1)).submit(fn, *args, **kwargs)


def _flat_u8(buf) -> np.ndarray:
    """A flat uint8 view over any C-contiguous buffer (zero-copy)."""
    if isinstance(buf, np.ndarray):
        if not buf.flags.c_contiguous:
            raise ValueError(
                "parallel copy requires C-contiguous arrays"
            )
        return buf.reshape(-1).view(np.uint8)
    mv = memoryview(buf)
    if not mv.contiguous:
        raise ValueError("parallel copy requires contiguous buffers")
    return np.frombuffer(mv.cast("B"), dtype=np.uint8)


def parallel_memcpy(dst, src, workers: Optional[int] = None,
                    chunk: Optional[int] = None) -> int:
    """Copy ``src`` into ``dst`` (equal byte length, both contiguous)
    across the worker pool on disjoint chunks; returns bytes copied.

    Byte-identical to ``np.copyto`` for every worker count — chunking
    only partitions the range — so workers=1 vs N is a pure speed
    knob.  Small copies (< MIN_PARALLEL_BYTES) stay serial: dispatch
    overhead would dominate.
    """
    d = _flat_u8(dst)
    s = _flat_u8(src)
    if d.nbytes != s.nbytes:
        raise ValueError(
            f"size mismatch: dst={d.nbytes} src={s.nbytes} bytes"
        )
    total = d.nbytes
    workers = workers if workers is not None else copy_workers()
    chunk = chunk or chunk_nbytes()
    if workers <= 1 or total < max(MIN_PARALLEL_BYTES, 2 * chunk):
        np.copyto(d, s)
        return total
    pool = _get_pool(workers)
    futures = [
        pool.submit(np.copyto, d[off:off + n], s[off:off + n])
        for off, n in chunked_iter(total, chunk)
    ]
    for f in futures:
        f.result()
    return total


def _fill_slice(view: np.ndarray, value: int):
    view.fill(value)


def parallel_fill(dst, value: int = 0, workers: Optional[int] = None,
                  chunk: Optional[int] = None) -> int:
    """Fill ``dst`` with ``value`` across the pool; returns the bytes
    touched.  The point is page-touch parallelism: first-touch faults
    of a fresh (tmpfs or anonymous) mapping serialize on one core
    otherwise — the measured preallocation bottleneck."""
    d = _flat_u8(dst)
    total = d.nbytes
    workers = workers if workers is not None else copy_workers()
    chunk = chunk or chunk_nbytes()
    if workers <= 1 or total < max(MIN_PARALLEL_BYTES, 2 * chunk):
        d.fill(value)
        return total
    pool = _get_pool(workers)
    futures = [
        pool.submit(_fill_slice, d[off:off + n], value)
        for off, n in chunked_iter(total, chunk)
    ]
    for f in futures:
        f.result()
    return total


def throughput_gbps(nbytes: int, seconds: float) -> float:
    """GB/s with a zero-duration guard, rounded to 4 significant
    digits for span labels (fixed decimals would round a KB-scale
    test state's bandwidth to 0.0 and break the >0 invariant)."""
    gbps = nbytes / 1e9 / max(seconds, 1e-9)
    return float(f"{gbps:.4g}")
