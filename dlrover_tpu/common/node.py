"""Node model: resources + lifecycle bookkeeping for a TPU-VM worker.

Reference parity: ``dlrover/python/common/node.py:37,124,149``
(NodeResource / NodeGroupResource / Node).  TPU twist: the resource unit
is a TPU-VM worker with N chips on an ICI slice; ``tpu_topology`` carries
the slice shape instead of gpu_type.
"""

import copy
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from dlrover_tpu.common.constants import (
    NodeExitReason,
    NodeStatus,
    NodeType,
)


@dataclass
class NodeResource:
    """Resources of a node.

    cpu: cores; memory: MiB; tpu_chips: chips attached to this worker;
    tpu_type: e.g. "v5e"; tpu_topology: e.g. "4x4".
    """

    cpu: float = 0.0
    memory: int = 0
    tpu_chips: int = 0
    tpu_type: str = ""
    tpu_topology: str = ""
    priority: str = ""

    @classmethod
    def resource_str_to_node_resource(cls, resource: str) -> "NodeResource":
        """Parse "cpu=4,memory=8192,tpu_chips=4" style strings."""
        kwargs: Dict[str, object] = {}
        for item in resource.split(","):
            if not item.strip():
                continue
            key, _, value = item.partition("=")
            key = key.strip().lower()
            value = value.strip()
            if key == "cpu":
                kwargs["cpu"] = float(value)
            elif key in ("memory", "mem"):
                kwargs["memory"] = int(value.lower().rstrip("mi"))
            elif key == "tpu_chips":
                kwargs["tpu_chips"] = int(value)
            elif key == "tpu_type":
                kwargs["tpu_type"] = value
            elif key == "tpu_topology":
                kwargs["tpu_topology"] = value
        return cls(**kwargs)  # type: ignore[arg-type]


@dataclass
class NodeGroupResource:
    """Replica-group resource spec (count x per-node resource)."""

    count: int = 0
    node_resource: NodeResource = field(default_factory=NodeResource)

    def update(self, count: int = 0, cpu: float = 0, memory: int = 0):
        if count > 0:
            self.count = count
        if cpu > 0:
            self.node_resource.cpu = cpu
        if memory > 0:
            self.node_resource.memory = memory


class Node:
    """A supervised node with status/rank/relaunch bookkeeping."""

    def __init__(
        self,
        node_type: str = NodeType.WORKER,
        node_id: int = 0,
        rank_index: Optional[int] = None,
        name: str = "",
        status: str = NodeStatus.INITIAL,
        config_resource: Optional[NodeResource] = None,
        max_relaunch_count: int = 3,
        relaunchable: bool = True,
        critical: bool = False,
    ):
        self.type = node_type
        self.id = node_id
        self.rank_index = rank_index if rank_index is not None else node_id
        self.name = name or f"{node_type}-{node_id}"
        self.status = status
        self.config_resource = config_resource or NodeResource()
        self.used_resource = NodeResource()
        self.max_relaunch_count = max_relaunch_count
        self.relaunch_count = 0
        self.relaunchable = relaunchable
        self.critical = critical
        self.exit_reason = ""
        self.create_time: Optional[float] = None
        self.start_time: Optional[float] = None
        self.finish_time: Optional[float] = None
        self.heartbeat_time: float = 0.0
        self.host_addr = ""
        self.host_port = 0
        self.restart_training = False
        self.paral_config = None
        self.start_hang_time: float = 0.0
        self.reported_status = NodeStatus.INITIAL
        self.is_released = False
        self.group: Optional[int] = None

    def update_status(self, status: str):
        if status != NodeStatus.UNKNOWN:
            self.status = status
        if status == NodeStatus.RUNNING and self.start_time is None:
            self.start_time = time.time()
        if status in NodeStatus.end_states():
            self.finish_time = time.time()

    def inc_relaunch_count(self):
        self.relaunch_count += 1

    def exceeded_max_relaunch(self) -> bool:
        return self.relaunch_count >= self.max_relaunch_count

    def update_node_check_result(self, succeeded: bool, elapsed: float):
        self.check_succeeded = succeeded
        self.check_elapsed = elapsed

    def set_exit_reason(self, reason: str):
        self.exit_reason = reason
        # only ever *clear* relaunchable; a node marked non-relaunchable
        # stays retired regardless of later exit reasons
        if reason == NodeExitReason.FATAL_ERROR:
            self.relaunchable = False

    def is_unrecoverable_failure(self) -> bool:
        if not self.relaunchable:
            return True
        if self.exceeded_max_relaunch():
            return True
        return self.exit_reason == NodeExitReason.FATAL_ERROR

    def timeout(self, timeout_secs: float) -> bool:
        now = time.time()
        if (
            self.heartbeat_time > 0
            and now - self.heartbeat_time > timeout_secs
            and self.status == NodeStatus.RUNNING
        ):
            return True
        return False

    def get_relaunch_node(self, new_id: int) -> "Node":
        """Build the replacement node after a relaunch decision."""
        new_node = copy.deepcopy(self)
        new_node.id = new_id
        new_node.name = f"{self.type}-{new_id}"
        new_node.status = NodeStatus.INITIAL
        new_node.start_time = None
        new_node.finish_time = None
        new_node.create_time = None
        new_node.is_released = False
        new_node.exit_reason = ""
        new_node.heartbeat_time = 0
        new_node.relaunch_count = self.relaunch_count
        return new_node

    def __repr__(self):  # pragma: no cover - debugging aid
        return (
            f"Node(type={self.type}, id={self.id}, rank={self.rank_index},"
            f" status={self.status})"
        )
