"""Sparse embedding layer: host KvTable gather -> TPU dense compute.

Reference parity: tfplus ``embedding_ops.py`` +
``kv_variable_ops.py``'s saver patching.  The TPU split: the unbounded
id space lives in host memory (C++ table), each step gathers the
batch's rows into a dense [B, dim] array that goes to the device; the
backward path scatters row gradients back into the table (sparse
update, no dense embedding matrix ever exists).  This is the classic
host-offload recommendation engine pattern.
"""

from typing import Optional

import numpy as np

from dlrover_tpu.sparse.kv_table import KvTable


class SparseEmbedding:
    def __init__(
        self,
        dim: int,
        init_stddev: float = 0.01,
        seed: int = 0,
        learning_rate: float = 0.01,
    ):
        self.table = KvTable(dim, init_stddev=init_stddev, seed=seed)
        self.dim = dim
        self.learning_rate = learning_rate
        self._last_keys: Optional[np.ndarray] = None

    def lookup(self, ids: np.ndarray, training: bool = True) -> np.ndarray:
        """[...] int64 ids -> [..., dim] float32 (feed to jax)."""
        ids = np.asarray(ids, dtype=np.int64)
        if training:
            self._last_keys = ids.reshape(-1)
        return self.table.gather(
            ids, insert_missing=training, count_frequency=training
        )

    def apply_gradients(self, grads: np.ndarray,
                        ids: Optional[np.ndarray] = None):
        """grads [..., dim] aligned with the last lookup (or given ids)."""
        keys = (
            np.asarray(ids, dtype=np.int64).reshape(-1)
            if ids is not None
            else self._last_keys
        )
        if keys is None:
            raise RuntimeError("no lookup recorded before update")
        grads = np.asarray(grads, dtype=np.float32).reshape(
            keys.size, self.dim
        )
        # duplicate ids within the batch must accumulate before SGD
        uniq, inverse = np.unique(keys, return_inverse=True)
        summed = np.zeros((uniq.size, self.dim), dtype=np.float32)
        np.add.at(summed, inverse, grads)
        self.table.apply_gradients(uniq, summed, self.learning_rate)

    # ---------------------------------------------------------- ckpt
    def state_dict(self) -> dict:
        keys, values = self.table.export()
        return {"keys": keys, "values": values, "dim": self.dim}

    def load_state_dict(self, state: dict):
        if int(state["dim"]) != self.dim:
            raise ValueError("embedding dim mismatch")
        self.table.import_(state["keys"], state["values"])
