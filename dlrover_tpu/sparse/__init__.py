from dlrover_tpu.sparse.kv_table import KvTable  # noqa: F401
from dlrover_tpu.sparse.embedding import SparseEmbedding  # noqa: F401
from dlrover_tpu.sparse.checkpoint import (  # noqa: F401
    SparseCheckpointManager,
)
from dlrover_tpu.sparse.kv_table import gather_batch  # noqa: F401
