"""Sparse-table checkpoint manager: full + delta chains.

Reference parity: tfplus's saver integration
(``tfplus/tfplus/kv_variable/python/ops/checkpoint_manager.py`` and the
delta-export switches of ``kv_variable_ops.py:198-273``) — KvVariable
tables checkpoint **incrementally**: a full export periodically, then
only the rows touched since the previous save.

The TPU-build form works over the same pluggable
:class:`~dlrover_tpu.common.storage.CheckpointStorage` the flash
checkpoint uses, with the same two-phase commit discipline (write into
a hidden tmp dir, rename to the committed name) so a crash mid-save
never corrupts a restore source.  Layout::

    <dir>/step-00000010/manifest.json   # kind: full | delta(base_step)
                        <table>.keys.npy
                        <table>.values.npy

Restore walks the chain: the newest full save at-or-before the
requested step, then every delta after it in step order, applied with
``KvTable.import_`` (last-writer-wins per row — delta semantics).
"""

import io
import os
import queue
import threading
from typing import Dict, List, Optional

import numpy as np

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.storage import (
    CheckpointStorage,
    get_checkpoint_storage,
)

_STEP_PREFIX = "step-"
_TMP_PREFIX = "._tmp-"


def _step_dir(step: int) -> str:
    return f"{_STEP_PREFIX}{step:08d}"


def _npy_bytes(arr: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, arr)
    return buf.getvalue()


def _npy_load(raw: bytes) -> np.ndarray:
    return np.load(io.BytesIO(raw), allow_pickle=False)


class SparseCheckpointManager:
    """Checkpoint a named set of :class:`KvTable`-like objects
    (anything with ``export``/``export_delta``/``import_``/
    ``version``).

    ``full_every`` controls the chain length: every N-th save is a
    full export, the rest are deltas against the previous save's cut
    version.  ``max_chains_to_keep`` bounds disk: cleanup removes the
    oldest full save together with its dependent deltas.
    """

    def __init__(
        self,
        ckpt_dir: str,
        storage: Optional[CheckpointStorage] = None,
        full_every: int = 5,
        max_chains_to_keep: int = 2,
    ):
        self.dir = ckpt_dir
        # path-aware default: a gs://… chain dir must select the
        # object-store tier like the dense engine does — defaulting to
        # POSIX would silently strand sparse state on the VM-local
        # disk the object tier exists to outlive
        self.storage = storage or get_checkpoint_storage(path=ckpt_dir)
        self.full_every = max(1, full_every)
        self.max_chains = max(1, max_chains_to_keep)
        self.storage.safe_makedirs(ckpt_dir)
        # per-table cut version of the LAST committed save; deltas
        # export rows touched after it
        self._last_cut: Dict[str, int] = {}
        self._saves_since_full = 0
        self._last_step: Optional[int] = None
        # a lost async write breaks the delta chain; force the next
        # save to be full when one fails (guarded: the writer thread
        # sets it, save() reads+clears it)
        self._force_full = False
        self._flag_lock = threading.Lock()
        self._io_queue: Optional[queue.Queue] = None
        self._io_thread: Optional[threading.Thread] = None

    # ---------------------------------------------------- async writer

    def _ensure_io_thread(self):
        if self._io_thread is not None:
            return
        self._io_queue = queue.Queue()
        self._pending = 0
        self._pending_cv = threading.Condition()

        def _loop():
            while True:
                item = self._io_queue.get()
                step, manifest, payload = item
                try:
                    self._write_commit(step, manifest, payload)
                except Exception as e:  # noqa: BLE001
                    logger.error(
                        "sparse ckpt async write for step %s failed: "
                        "%s — forcing next save full", step, e,
                    )
                    with self._flag_lock:
                        self._force_full = True
                finally:
                    with self._pending_cv:
                        self._pending -= 1
                        self._pending_cv.notify_all()

        self._io_thread = threading.Thread(
            target=_loop, name="sparse-ckpt-writer", daemon=True
        )
        self._io_thread.start()

    def wait_for_writes(self, timeout: float = 600.0):
        """Join all queued async writes (call before process exit)."""
        if self._io_thread is None:
            return
        with self._pending_cv:
            if not self._pending_cv.wait_for(
                lambda: self._pending == 0, timeout=timeout
            ):
                logger.warning("sparse ckpt writes still pending")

    # ------------------------------------------------------------ save

    def save(
        self,
        step: int,
        tables: Dict,
        full: Optional[bool] = None,
        blocking: bool = True,
    ) -> str:
        """Persist ``tables`` at ``step``; returns the committed dir.

        ``full=None`` -> automatic cadence (first save and every
        ``full_every``-th are full).  ``blocking=False`` exports the
        rows inline (the version cut must happen NOW) but hands
        serialization + storage writes + commit to a background writer
        thread — the train step is blocked only for the row memcpy,
        mirroring the dense engine's async persist.  Call
        :meth:`wait_for_writes` before process exit."""
        final = os.path.join(self.dir, _step_dir(step))
        if self.storage.exists(final):
            # a committed dir for this step exists: only legal as an
            # idempotent re-save of the SAME timeline (the final save
            # in a train loop repeating the last interval step);
            # restore() truncates ahead-of-restore steps, so an
            # abandoned-timeline dir cannot survive to reach here
            return final
        with self._flag_lock:
            if full is None:
                full = (
                    not self._last_cut
                    or self._force_full
                    or self._saves_since_full >= self.full_every - 1
                )
            if full:
                # only a FULL save repairs a broken chain; an explicit
                # full=False must not consume the recovery flag
                self._force_full = False
        kind = "full" if full else "delta"
        manifest = {
            "step": step,
            "kind": kind,
            "base_step": self._last_step if not full else None,
            "tables": {},
        }
        cuts: Dict[str, int] = {}
        payload: Dict[str, tuple] = {}
        for name, table in tables.items():
            if full:
                cut = table.version
                keys, values = table.export()
            else:
                since = self._last_cut.get(name, 0)
                keys, values, cut = table.export_delta(since)
            cuts[name] = cut
            payload[name] = (keys, values)
            manifest["tables"][name] = {
                "count": int(keys.size),
                "dim": int(values.shape[1]) if values.ndim == 2 else 0,
                "cut_version": int(cut),
            }
        # bookkeeping advances at the cut, not the commit: the next
        # delta must not re-export these rows (a lost async write is
        # recovered by _force_full, and across processes by restore()
        # re-reading the last COMMITTED manifest)
        self._last_cut = cuts
        self._last_step = step
        self._saves_since_full = 0 if full else self._saves_since_full + 1
        logger.info(
            "sparse ckpt %s save at step %s (%s rows%s)",
            kind,
            step,
            sum(m["count"] for m in manifest["tables"].values()),
            ", async" if not blocking else "",
        )
        if blocking:
            self._write_commit(step, manifest, payload)
        else:
            self._ensure_io_thread()
            with self._pending_cv:
                self._pending += 1
            self._io_queue.put((step, manifest, payload))
        return final

    def _write_commit(self, step: int, manifest: dict, payload: Dict):
        tmp = os.path.join(self.dir, _TMP_PREFIX + _step_dir(step))
        final = os.path.join(self.dir, _step_dir(step))
        self.storage.safe_makedirs(tmp)
        for name, (keys, values) in payload.items():
            self.storage.write(
                _npy_bytes(keys), os.path.join(tmp, f"{name}.keys.npy")
            )
            self.storage.write(
                _npy_bytes(values),
                os.path.join(tmp, f"{name}.values.npy"),
            )
        self.storage.write_json(
            manifest, os.path.join(tmp, "manifest.json")
        )
        self.storage.safe_move(tmp, final)  # commit
        self._cleanup()

    # --------------------------------------------------------- restore

    def _manifests(self) -> List[dict]:
        out = []
        for entry in sorted(self.storage.listdir(self.dir)):
            if not entry.startswith(_STEP_PREFIX):
                continue
            m = self.storage.read_json(
                os.path.join(self.dir, entry, "manifest.json")
            )
            if m is not None:
                out.append(m)
        return out

    def latest_step(self) -> Optional[int]:
        manifests = self._manifests()
        return manifests[-1]["step"] if manifests else None

    def restore(self, tables: Dict, step: Optional[int] = None):
        """Load the newest CONSISTENT save at-or-before ``step``
        (default: the newest committed save) into ``tables``; returns
        the restored step or None when nothing is committed.

        Consistency: a delta is only restorable when its ``base_step``
        is the immediately preceding committed save and that save is
        itself consistent — a failed async write leaves a hole, and
        deltas committed past the hole reference rows the chain no
        longer carries; those are skipped (with a warning), falling
        back to the newest consistent prefix."""
        manifests = self._manifests()
        if step is not None:
            manifests = [m for m in manifests if m["step"] <= step]
        if not manifests:
            return None
        # forward pass: a full restarts consistency; a delta is
        # consistent iff it chains to the previous consistent save
        consistent: List[dict] = []
        prev_ok: Optional[dict] = None
        for m in manifests:
            if m["kind"] == "full":
                prev_ok = m
                consistent.append(m)
            elif (
                prev_ok is not None
                and m.get("base_step") == prev_ok["step"]
            ):
                prev_ok = m
                consistent.append(m)
            else:
                logger.warning(
                    "sparse ckpt: delta at step %s has no consistent "
                    "base (hole in the chain) — ignoring it and "
                    "everything after it until the next full save",
                    m["step"],
                )
                prev_ok = None
        if not consistent:
            raise RuntimeError(
                "sparse ckpt chain has no restorable save — every "
                "committed delta is missing its base"
            )
        target = consistent[-1]
        # chain: newest full at-or-before target, then deltas upward
        chain: List[dict] = []
        for m in reversed(consistent):
            if m["step"] > target["step"]:
                continue
            chain.append(m)
            if m["kind"] == "full":
                break
        chain.reverse()
        # read EVERY chain payload before mutating the live tables: a
        # missing/torn file (crash mid-commit on a non-atomic object
        # store) must fail the restore with the live rows intact
        loaded = []  # per chain link: {name: (keys, values)}
        for m in chain:
            d = os.path.join(self.dir, _step_dir(m["step"]))
            payload = {}
            for name in tables:
                if name not in m["tables"]:
                    continue
                payload[name] = (
                    _npy_load(
                        self.storage.read(
                            os.path.join(d, f"{name}.keys.npy"), "rb"
                        )
                    ),
                    _npy_load(
                        self.storage.read(
                            os.path.join(d, f"{name}.values.npy"),
                            "rb",
                        )
                    ),
                )
            loaded.append(payload)
        # restore-in-place must rewind EXACTLY: rows inserted after
        # the restore point are not expressible as delta removals, so
        # every table the CHAIN touches (a delta target may omit a
        # table an earlier full carries) is cleared before re-import —
        # otherwise phantom rows survive and diverge from the dense
        # state restored alongside.
        chain_names = set()
        for payload in loaded:
            chain_names.update(payload)
        for name in chain_names:
            table = tables[name]
            if hasattr(table, "clear"):
                dropped = table.clear()
                if dropped:
                    logger.info(
                        "sparse ckpt: cleared %s live rows from %s "
                        "before restore", dropped, name,
                    )
            else:
                # clearing is REQUIRED for an exact rewind; a table
                # type without clear() keeps whatever rows were
                # inserted after the restore point (ADVICE-r4: the
                # phantom-row risk must be visible, not silent)
                logger.warning(
                    "sparse ckpt: table %s has no clear(); rows "
                    "written after the restore point survive the "
                    "rewind (phantom-row risk)", name,
                )
        for payload in loaded:
            for name, (keys, values) in payload.items():
                if keys.size:
                    tables[name].import_(keys, values)
        # the timeline is rewound to target: committed saves NEWER
        # than it belong to an abandoned run — a later re-save of
        # those steps would otherwise be silently skipped by the
        # idempotence check and corrupt the delta chain with
        # old-timeline rows
        for m in self._manifests():
            if m["step"] > target["step"]:
                logger.info(
                    "sparse ckpt: dropping abandoned-timeline step %s",
                    m["step"],
                )
                self.storage.safe_rmtree(
                    os.path.join(self.dir, _step_dir(m["step"]))
                )
        # future deltas continue from the restored chain's head
        self._last_cut = {
            name: meta["cut_version"]
            for name, meta in target["tables"].items()
        }
        self._last_step = target["step"]
        self._saves_since_full = 0
        return target["step"]

    # --------------------------------------------------------- cleanup

    def _cleanup(self):
        """Drop the oldest full-save chains beyond ``max_chains``;
        a delta is only ever deleted together with (or before) its
        base, so every surviving save remains restorable."""
        manifests = self._manifests()
        full_steps = [
            m["step"] for m in manifests if m["kind"] == "full"
        ]
        if len(full_steps) <= self.max_chains:
            return
        # keep the newest max_chains fulls; everything strictly older
        # than the oldest kept full (fulls AND their deltas) goes
        cutoff = sorted(full_steps)[-self.max_chains]
        for m in manifests:
            if m["step"] < cutoff:
                self.storage.safe_rmtree(
                    os.path.join(self.dir, _step_dir(m["step"]))
                )
