"""Sparse optimizers over the KvTable (Adam / Adagrad family).

Reference parity: tfplus's sparse training kernels
(``kv_variable/kernels/training_ops.cc`` — Adagrad, Adam, GroupAdam
etc. applied per touched row).  Moments live in sibling KvTables so
state grows with the touched-id set, exactly like the reference's
slot variables.
"""

from typing import Dict

import numpy as np

from dlrover_tpu.sparse.kv_table import KvTable


class SparseAdam:
    def __init__(
        self,
        table: KvTable,
        learning_rate: float = 1e-3,
        b1: float = 0.9,
        b2: float = 0.999,
        eps: float = 1e-8,
    ):
        self.table = table
        self.lr = learning_rate
        self.b1, self.b2, self.eps = b1, b2, eps
        self._m = KvTable(table.dim)
        self._v = KvTable(table.dim)
        self._step = 0

    def update(self, keys: np.ndarray, grads: np.ndarray):
        keys = np.asarray(keys, dtype=np.int64).reshape(-1)
        grads = np.asarray(grads, dtype=np.float32).reshape(
            keys.size, self.table.dim
        )
        uniq, inverse = np.unique(keys, return_inverse=True)
        g = np.zeros((uniq.size, self.table.dim), dtype=np.float32)
        np.add.at(g, inverse, grads)

        self._step += 1
        m = self._m.gather(uniq, count_frequency=False)
        v = self._v.gather(uniq, count_frequency=False)
        m = self.b1 * m + (1 - self.b1) * g
        v = self.b2 * v + (1 - self.b2) * g * g
        self._m.scatter(uniq, m)
        self._v.scatter(uniq, v)
        bc1 = 1 - self.b1**self._step
        bc2 = 1 - self.b2**self._step
        update = self.lr * (m / bc1) / (np.sqrt(v / bc2) + self.eps)
        self.table.scatter(uniq, update, op=KvTable.SCATTER_SUB)

    def state_dict(self) -> Dict:
        mk, mv = self._m.export()
        vk, vv = self._v.export()
        return {
            "step": self._step,
            "m_keys": mk, "m_values": mv,
            "v_keys": vk, "v_values": vv,
        }

    def load_state_dict(self, state: Dict):
        self._step = int(state["step"])
        self._m.import_(state["m_keys"], state["m_values"])
        self._v.import_(state["v_keys"], state["v_values"])


class SparseAdagrad:
    def __init__(self, table: KvTable, learning_rate: float = 0.1,
                 eps: float = 1e-10):
        self.table = table
        self.lr = learning_rate
        self.eps = eps
        self._accum = KvTable(table.dim)

    def update(self, keys: np.ndarray, grads: np.ndarray):
        keys = np.asarray(keys, dtype=np.int64).reshape(-1)
        grads = np.asarray(grads, dtype=np.float32).reshape(
            keys.size, self.table.dim
        )
        uniq, inverse = np.unique(keys, return_inverse=True)
        g = np.zeros((uniq.size, self.table.dim), dtype=np.float32)
        np.add.at(g, inverse, grads)
        acc = self._accum.gather(uniq, count_frequency=False)
        acc = acc + g * g
        self._accum.scatter(uniq, acc)
        update = self.lr * g / (np.sqrt(acc) + self.eps)
        self.table.scatter(uniq, update, op=KvTable.SCATTER_SUB)
