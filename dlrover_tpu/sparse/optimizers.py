"""Sparse optimizers over the KvTable.

Reference parity: tfplus's sparse training kernels
(``kv_variable/kernels/training_ops.cc:7236`` — Adagrad, Adam,
GroupAdam, GroupAdagrad, SparseGroupFtrl, RectifiedAdam applied per
touched row).  Moments live in sibling KvTables so state grows with
the touched-id set, exactly like the reference's slot variables.

The "Group" family adds group-lasso regularization at embedding-row
granularity: after the base update, each row is shrunk toward zero as
a whole (``w *= max(0, 1 - lr*l21/||w||)``) so unused/noisy ids prune
to exact zeros — the feature-selection behavior the reference's group
optimizers exist for.
"""

from typing import Dict

import numpy as np

from dlrover_tpu.sparse.kv_table import KvTable


def _dedup(keys: np.ndarray, grads: np.ndarray, dim: int):
    keys = np.asarray(keys, dtype=np.int64).reshape(-1)
    grads = np.asarray(grads, dtype=np.float32).reshape(keys.size, dim)
    uniq, inverse = np.unique(keys, return_inverse=True)
    g = np.zeros((uniq.size, dim), dtype=np.float32)
    np.add.at(g, inverse, grads)
    return uniq, g


def _group_shrink(table: KvTable, keys: np.ndarray, strength: float):
    """Row-wise group-lasso proximal step: shrink each touched row
    toward zero as a unit; rows whose norm falls below the threshold
    become exact zeros (feature pruning)."""
    if strength <= 0:
        return
    w = table.gather(keys, count_frequency=False)
    norms = np.linalg.norm(w, axis=1, keepdims=True)
    scale = np.maximum(0.0, 1.0 - strength / np.maximum(norms, 1e-12))
    table.scatter(keys, w * scale)


class SparseAdam:
    def __init__(
        self,
        table: KvTable,
        learning_rate: float = 1e-3,
        b1: float = 0.9,
        b2: float = 0.999,
        eps: float = 1e-8,
    ):
        self.table = table
        self.lr = learning_rate
        self.b1, self.b2, self.eps = b1, b2, eps
        self._m = KvTable(table.dim)
        self._v = KvTable(table.dim)
        self._step = 0

    def update(self, keys: np.ndarray, grads: np.ndarray):
        uniq, g = _dedup(keys, grads, self.table.dim)

        self._step += 1
        m = self._m.gather(uniq, count_frequency=False)
        v = self._v.gather(uniq, count_frequency=False)
        m = self.b1 * m + (1 - self.b1) * g
        v = self.b2 * v + (1 - self.b2) * g * g
        self._m.scatter(uniq, m)
        self._v.scatter(uniq, v)
        bc1 = 1 - self.b1**self._step
        bc2 = 1 - self.b2**self._step
        update = self.lr * (m / bc1) / (np.sqrt(v / bc2) + self.eps)
        self.table.scatter(uniq, update, op=KvTable.SCATTER_SUB)
        return uniq

    def state_dict(self) -> Dict:
        mk, mv = self._m.export()
        vk, vv = self._v.export()
        return {
            "step": self._step,
            "m_keys": mk, "m_values": mv,
            "v_keys": vk, "v_values": vv,
        }

    def load_state_dict(self, state: Dict):
        self._step = int(state["step"])
        self._m.import_(state["m_keys"], state["m_values"])
        self._v.import_(state["v_keys"], state["v_values"])


class SparseAdagrad:
    def __init__(self, table: KvTable, learning_rate: float = 0.1,
                 eps: float = 1e-10):
        self.table = table
        self.lr = learning_rate
        self.eps = eps
        self._accum = KvTable(table.dim)

    def update(self, keys: np.ndarray, grads: np.ndarray):
        uniq, g = _dedup(keys, grads, self.table.dim)
        acc = self._accum.gather(uniq, count_frequency=False)
        acc = acc + g * g
        self._accum.scatter(uniq, acc)
        update = self.lr * g / (np.sqrt(acc) + self.eps)
        self.table.scatter(uniq, update, op=KvTable.SCATTER_SUB)
        return uniq


class SparseGroupAdam(SparseAdam):
    """Adam + row-wise group lasso (ref ``GroupAdam``)."""

    def __init__(self, table: KvTable, learning_rate: float = 1e-3,
                 l21: float = 0.0, **kwargs):
        super().__init__(table, learning_rate, **kwargs)
        self.l21 = l21

    def update(self, keys: np.ndarray, grads: np.ndarray):
        uniq = super().update(keys, grads)
        _group_shrink(self.table, uniq, self.lr * self.l21)
        return uniq


class SparseGroupAdagrad(SparseAdagrad):
    """Adagrad + row-wise group lasso (ref ``GroupAdagrad``)."""

    def __init__(self, table: KvTable, learning_rate: float = 0.1,
                 l21: float = 0.0, **kwargs):
        super().__init__(table, learning_rate, **kwargs)
        self.l21 = l21

    def update(self, keys: np.ndarray, grads: np.ndarray):
        uniq = super().update(keys, grads)
        _group_shrink(self.table, uniq, self.lr * self.l21)
        return uniq


class SparseGroupFtrl:
    """FTRL-proximal with per-row group lasso (ref ``SparseGroupFtrl``
    ``training_ops.cc``): z/n accumulators per touched row; a row whose
    ||z|| stays under the l21 threshold snaps to exact zero."""

    def __init__(
        self,
        table: KvTable,
        learning_rate: float = 0.1,
        beta: float = 1.0,
        l1: float = 0.0,
        l2: float = 0.0,
        l21: float = 0.0,
    ):
        self.table = table
        self.lr = learning_rate
        self.beta = beta
        self.l1, self.l2, self.l21 = l1, l2, l21
        self._z = KvTable(table.dim)
        self._n = KvTable(table.dim)

    def update(self, keys: np.ndarray, grads: np.ndarray):
        uniq, g = _dedup(keys, grads, self.table.dim)
        w = self.table.gather(uniq, count_frequency=False)
        z = self._z.gather(uniq, count_frequency=False)
        n = self._n.gather(uniq, count_frequency=False)
        n_new = n + g * g
        sigma = (np.sqrt(n_new) - np.sqrt(n)) / self.lr
        z = z + g - sigma * w
        self._z.scatter(uniq, z)
        self._n.scatter(uniq, n_new)

        # per-coordinate l1 shrink, then per-row group threshold
        z_shrunk = np.sign(z) * np.maximum(np.abs(z) - self.l1, 0.0)
        denom = (self.beta + np.sqrt(n_new)) / self.lr + self.l2
        row_norm = np.linalg.norm(
            z_shrunk, axis=1, keepdims=True
        )
        group_scale = np.maximum(
            0.0, 1.0 - self.l21 / np.maximum(row_norm, 1e-12)
        )
        w_new = -(z_shrunk * group_scale) / denom
        self.table.scatter(uniq, w_new)
        return uniq

    def state_dict(self) -> Dict:
        zk, zv = self._z.export()
        nk, nv = self._n.export()
        return {
            "z_keys": zk, "z_values": zv,
            "n_keys": nk, "n_values": nv,
        }

    def load_state_dict(self, state: Dict):
        self._z.import_(state["z_keys"], state["z_values"])
        self._n.import_(state["n_keys"], state["n_values"])


class SparseRAdam(SparseAdam):
    """Rectified Adam (ref ``RectifiedAdam`` sparse kernel): the
    adaptive term is variance-rectified and disabled during the early
    steps where the second-moment estimate is unreliable."""

    def update(self, keys: np.ndarray, grads: np.ndarray):
        uniq, g = _dedup(keys, grads, self.table.dim)

        self._step += 1
        t = self._step
        m = self._m.gather(uniq, count_frequency=False)
        v = self._v.gather(uniq, count_frequency=False)
        m = self.b1 * m + (1 - self.b1) * g
        v = self.b2 * v + (1 - self.b2) * g * g
        self._m.scatter(uniq, m)
        self._v.scatter(uniq, v)

        rho_inf = 2.0 / (1.0 - self.b2) - 1.0
        b2t = self.b2**t
        rho = rho_inf - 2.0 * t * b2t / (1.0 - b2t)
        m_hat = m / (1 - self.b1**t)
        if rho > 4.0:
            r = np.sqrt(
                ((rho - 4) * (rho - 2) * rho_inf)
                / ((rho_inf - 4) * (rho_inf - 2) * rho)
            )
            v_hat = np.sqrt(v / (1 - b2t))
            update = self.lr * r * m_hat / (v_hat + self.eps)
        else:
            update = self.lr * m_hat  # un-adapted SGD-with-momentum
        self.table.scatter(uniq, update, op=KvTable.SCATTER_SUB)
        return uniq
