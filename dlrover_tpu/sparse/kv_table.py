"""ctypes binding for the native sparse embedding table.

Reference parity: the python glue of tfplus
(``tfplus/tfplus/python/ops/kv_variable_ops.py`` + ``embedding_ops.py``)
over the C++ table in ``native/kv_store/kv_table.cc``.  The shared
library is built on first use with g++ (no pybind11/bazel needed —
ctypes over a C API, as the environment prescribes).
"""

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.native_build import needs_rebuild, write_stamp

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_SRC = os.path.join(_REPO_ROOT, "native", "kv_store", "kv_table.cc")
_LIB_DIR = os.path.join(_REPO_ROOT, "native", "kv_store", "build")
_LIB = os.path.join(_LIB_DIR, "libkvtable.so")

_lib_handle = None
_build_lock = threading.Lock()


def _build_library() -> str:
    os.makedirs(_LIB_DIR, exist_ok=True)
    cmd = [
        "g++",
        "-O2",
        "-shared",
        "-fPIC",
        "-std=c++17",
        "-o",
        _LIB,
        _SRC,
        "-lpthread",
    ]
    logger.info("building kv_table: %s", " ".join(cmd))
    subprocess.run(cmd, check=True, capture_output=True)
    write_stamp(_LIB, _SRC)
    return _LIB


def _load_library():
    global _lib_handle
    with _build_lock:
        if _lib_handle is not None:
            return _lib_handle
        if needs_rebuild(_LIB, _SRC):
            _build_library()
        lib = ctypes.CDLL(_LIB)
        lib.kv_create.restype = ctypes.c_void_p
        lib.kv_create.argtypes = [
            ctypes.c_int,
            ctypes.c_float,
            ctypes.c_uint64,
        ]
        lib.kv_free.argtypes = [ctypes.c_void_p]
        lib.kv_dim.restype = ctypes.c_int
        lib.kv_dim.argtypes = [ctypes.c_void_p]
        lib.kv_size.restype = ctypes.c_uint64
        lib.kv_size.argtypes = [ctypes.c_void_p]
        lib.kv_gather.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int,
            ctypes.c_int,
        ]
        lib.kv_scatter.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int,
        ]
        lib.kv_frequency.restype = ctypes.c_uint64
        lib.kv_frequency.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.kv_export.restype = ctypes.c_int64
        lib.kv_export.argtypes = [
            ctypes.c_void_p,
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64,
        ]
        lib.kv_import.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_float),
        ]
        lib.kv_evict_below.restype = ctypes.c_int64
        lib.kv_evict_below.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.kv_clear.restype = ctypes.c_int64
        lib.kv_clear.argtypes = [ctypes.c_void_p]
        lib.kv_version.restype = ctypes.c_uint64
        lib.kv_version.argtypes = [ctypes.c_void_p]
        lib.kv_enable_spill.restype = ctypes.c_int
        lib.kv_enable_spill.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
        ]
        lib.kv_spill_below.restype = ctypes.c_int64
        lib.kv_spill_below.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.kv_spilled_count.restype = ctypes.c_uint64
        lib.kv_spilled_count.argtypes = [ctypes.c_void_p]
        lib.kv_export_delta.restype = ctypes.c_int64
        lib.kv_export_delta.argtypes = [
            ctypes.c_void_p,
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64,
        ]
        lib.kv_gather_batch.argtypes = [
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
            ctypes.c_int,
            ctypes.c_int,
        ]
        _lib_handle = lib
        return lib


def _i64_ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _f32_ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class KvTable:
    """Host-side dynamic embedding table (C++ backed)."""

    SCATTER_ASSIGN = 0
    SCATTER_ADD = 1
    SCATTER_SUB = 2

    def __init__(self, dim: int, init_stddev: float = 0.0,
                 seed: int = 0):
        self._lib = _load_library()
        self._handle = self._lib.kv_create(
            dim, ctypes.c_float(init_stddev), ctypes.c_uint64(seed)
        )
        if not self._handle:
            raise ValueError(f"bad embedding dim {dim}")
        self.dim = dim

    def close(self):
        if self._handle:
            self._lib.kv_free(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass

    def __len__(self) -> int:
        return int(self._lib.kv_size(self._handle))

    def gather(
        self,
        keys: np.ndarray,
        insert_missing: bool = True,
        count_frequency: bool = True,
    ) -> np.ndarray:
        keys = np.ascontiguousarray(keys, dtype=np.int64)
        out = np.empty((keys.size, self.dim), dtype=np.float32)
        self._lib.kv_gather(
            self._handle,
            _i64_ptr(keys),
            keys.size,
            _f32_ptr(out),
            1 if insert_missing else 0,
            1 if count_frequency else 0,
        )
        return out.reshape(keys.shape + (self.dim,))

    def scatter(self, keys: np.ndarray, updates: np.ndarray,
                op: int = SCATTER_ASSIGN):
        keys = np.ascontiguousarray(keys, dtype=np.int64).reshape(-1)
        updates = np.ascontiguousarray(
            updates, dtype=np.float32
        ).reshape(keys.size, self.dim)
        self._lib.kv_scatter(
            self._handle, _i64_ptr(keys), keys.size, _f32_ptr(updates), op
        )

    def apply_gradients(self, keys: np.ndarray, grads: np.ndarray,
                        learning_rate: float):
        """Sparse SGD on touched rows (the tfplus sparse-optimizer
        family lives in ``sparse/optimizers.py``)."""
        self.scatter(
            keys,
            np.asarray(grads, dtype=np.float32) * learning_rate,
            op=self.SCATTER_SUB,
        )

    def frequency(self, key: int) -> int:
        return int(self._lib.kv_frequency(self._handle, key))

    def export(
        self, min_frequency: int = 0
    ) -> Tuple[np.ndarray, np.ndarray]:
        count = int(
            self._lib.kv_export(
                self._handle,
                ctypes.c_uint64(min_frequency),
                None,
                None,
                0,
            )
        )
        if count == -2:
            raise RuntimeError(
                "spill record unreadable; export would be incomplete"
            )
        keys = np.empty(count, dtype=np.int64)
        values = np.empty((count, self.dim), dtype=np.float32)
        if count:
            written = int(
                self._lib.kv_export(
                    self._handle,
                    ctypes.c_uint64(min_frequency),
                    _i64_ptr(keys),
                    _f32_ptr(values),
                    count,
                )
            )
            if written == -2:
                raise RuntimeError(
                    "spill record unreadable; export would be "
                    "incomplete"
                )
            if written < 0:
                raise RuntimeError("kv_export capacity race")
            keys, values = keys[:written], values[:written]
        return keys, values

    def import_(self, keys: np.ndarray, values: np.ndarray):
        keys = np.ascontiguousarray(keys, dtype=np.int64)
        values = np.ascontiguousarray(values, dtype=np.float32)
        self._lib.kv_import(
            self._handle, _i64_ptr(keys), keys.size, _f32_ptr(values)
        )

    def clear(self) -> int:
        """Drop every row (RAM + spill tiers); returns removed count.
        Checkpoint restore-in-place clears before re-importing so rows
        inserted after the restore point cannot survive the rewind."""
        return int(self._lib.kv_clear(self._handle))

    def evict_below(self, min_frequency: int) -> int:
        return int(
            self._lib.kv_evict_below(
                self._handle, ctypes.c_uint64(min_frequency)
            )
        )

    # -- hybrid storage (disk tier) ----------------------------------------
    def enable_spill(self, path: str):
        """Attach a disk tier: cold rows move there via
        :meth:`spill_below` and fault back into RAM on access
        (reference hybrid storage, ``hybrid_embedding/
        table_manager.h:547``)."""
        rc = self._lib.kv_enable_spill(
            self._handle, path.encode()
        )
        if rc == -2:
            raise RuntimeError(
                "rows are already spilled; rotating the spill file "
                "would destroy them — gather them back or export first"
            )
        if rc != 0:
            raise OSError(f"cannot open spill file {path}")

    def spill_below(self, min_frequency: int) -> int:
        """Move rows colder than ``min_frequency`` to the disk tier
        (unlike :meth:`evict_below`, nothing is lost); returns the
        spilled count."""
        n = int(
            self._lib.kv_spill_below(
                self._handle, ctypes.c_uint64(min_frequency)
            )
        )
        if n < 0:
            raise RuntimeError("spill tier not enabled")
        return n

    @property
    def spilled_count(self) -> int:
        return int(self._lib.kv_spilled_count(self._handle))

    # -- delta checkpointing ----------------------------------------------
    @property
    def version(self) -> int:
        """Current mutation stamp; pass to :meth:`export_delta` later
        to persist only rows touched in between (reference delta
        export, ``kv_variable_ops.py:198-273``)."""
        return int(self._lib.kv_version(self._handle))

    def export_delta(
        self, since_version: int, max_retries: int = 8
    ) -> Tuple[np.ndarray, np.ndarray, int]:
        """(keys, values, cut_version) for rows updated after
        ``since_version`` — incremental checkpoints write this instead
        of the full table.

        Concurrent-training safe: the delta grows between the sizing
        call and the copy whenever a training thread touches rows, so
        the copy allocates headroom and retries with a fresh (larger)
        count when it still loses the race."""
        headroom = 1024
        for _ in range(max_retries):
            cut = self.version
            count = int(
                self._lib.kv_export_delta(
                    self._handle,
                    ctypes.c_uint64(since_version),
                    None,
                    None,
                    0,
                )
            )
            if count == -2:
                raise RuntimeError(
                    "spill record unreadable; delta export would be "
                    "incomplete"
                )
            capacity = count + headroom
            keys = np.empty(capacity, dtype=np.int64)
            values = np.empty((capacity, self.dim), dtype=np.float32)
            written = int(
                self._lib.kv_export_delta(
                    self._handle,
                    ctypes.c_uint64(since_version),
                    _i64_ptr(keys),
                    _f32_ptr(values),
                    capacity,
                )
            )
            if written == -2:
                raise RuntimeError(
                    "spill record unreadable; delta export would be "
                    "incomplete"
                )
            if written >= 0:
                return keys[:written], values[:written], cut
            headroom *= 4  # lost the race: grow and recount
        raise RuntimeError(
            "kv_export_delta kept losing the sizing race; table is "
            "being mutated faster than it can be scanned"
        )


def gather_batch(
    tables,
    keys_list,
    insert_missing: bool = True,
    count_frequency: bool = True,
):
    """Gather from many tables in ONE library crossing (reference
    ``BatchKvVariableGatherOrZerosV2``, tfplus ``kv_variable_ops.cc``
    batch ops): a recommender step looks up dozens of feature tables
    back to back — batching keeps the whole loop in C.

    ``tables``: list of :class:`KvTable` (dims may differ);
    ``keys_list``: one int64 array per table.  Returns one
    ``[*keys.shape, dim]`` fp32 array per table.
    """
    if len(tables) != len(keys_list):
        raise ValueError("one key array per table")
    if not tables:
        return []
    lib = tables[0]._lib
    n = len(tables)
    keys_np = [
        np.ascontiguousarray(k, dtype=np.int64) for k in keys_list
    ]
    flat = np.concatenate([k.reshape(-1) for k in keys_np])
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum([k.size for k in keys_np], out=offsets[1:])
    outs = [
        np.empty((k.size, t.dim), dtype=np.float32)
        for t, k in zip(tables, keys_np)
    ]
    handle_arr = (ctypes.c_void_p * n)(
        *[t._handle for t in tables]
    )
    out_arr = (ctypes.POINTER(ctypes.c_float) * n)(
        *[_f32_ptr(o) for o in outs]
    )
    lib.kv_gather_batch(
        handle_arr,
        n,
        _i64_ptr(flat),
        _i64_ptr(offsets),
        out_arr,
        1 if insert_missing else 0,
        1 if count_frequency else 0,
    )
    return [
        o.reshape(k.shape + (t.dim,))
        for o, k, t in zip(outs, keys_np, tables)
    ]
