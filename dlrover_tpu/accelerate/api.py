"""``auto_accelerate`` — one call from model to sharded train step.

Reference parity: ``atorch/atorch/auto/accelerate.py:406``
(``auto_accelerate(model, optim_func, dataset, loss_func, ...)`` →
namedtuple of transformed artifacts).  The TPU pipeline: analyse
(abstract shapes) → generate candidate meshes → optionally dry-run →
build the winning sharded train step.  Semi-auto: pass
``load_strategy=Strategy(...)`` to skip the search, exactly like the
reference's ``load_strategy`` path.
"""

from dataclasses import dataclass
from typing import Callable, Optional

import jax

from dlrover_tpu.accelerate.analyser import analyse_model
from dlrover_tpu.accelerate.strategy import Strategy, generate_candidates
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.parallel.mesh import create_parallel_mesh
from dlrover_tpu.parallel.sharding import default_rules
from dlrover_tpu.parallel.train_step import TrainStepFns, build_train_step


@dataclass
class AccelerateResult:
    fns: TrainStepFns
    strategy: Strategy
    mesh_ctx: object
    rules: object
    profile: object
    timings: dict
    # measurement-calibrated planner (None without a dry run): already
    # fitted on this run's timings — ``planner.plan(n_devices=256)``
    # ranks candidates at a larger target scale (profile small, plan
    # big; accelerate/dim_planner.py)
    planner: object = None


def _build_for_strategy(
    strategy: Strategy,
    loss_fn,
    optimizer,
    init_params_fn,
    param_axes,
    devices,
):
    mesh_ctx = create_parallel_mesh(
        strategy.mesh_dims(), devices=devices
    )
    if strategy.pipe > 1:
        mesh_ctx.pipeline_microbatches = (
            strategy.pipe_microbatches or 2 * strategy.pipe
        )
    rules = default_rules(**strategy.rule_flags())
    fns = build_train_step(
        loss_fn=loss_fn,
        optimizer=optimizer,
        init_params_fn=init_params_fn,
        param_axes=param_axes,
        mesh_ctx=mesh_ctx,
        rules=rules,
        num_micro_steps=strategy.num_micro_steps,
    )
    return fns, mesh_ctx, rules


def auto_accelerate(
    loss_fn: Callable,
    optimizer,
    init_params_fn: Callable,
    param_axes,
    sample_batch_fn: Optional[Callable] = None,
    devices=None,
    load_strategy: Optional[Strategy] = None,
    dry_run: bool = False,
    long_context: bool = False,
    moe: bool = False,
    batch_per_replica: int = 1,
    seq_len: int = 2048,
    global_batch: Optional[int] = None,
    tune_space: Optional[dict] = None,
    tune_budget: int = 6,
) -> AccelerateResult:
    """Args mirror ``build_train_step`` plus search knobs.

    ``batch_per_replica``/``seq_len`` describe the actual workload —
    the candidate cost model and the gradient-accumulation (micro
    step) search evaluate at these values, so passing the real numbers
    is what makes the ranking workload-aware.

    ``sample_batch_fn(batch_sharding) -> batch`` enables the timed dry
    run; without it (or with dry_run=False) the top-ranked memory-fit
    candidate wins directly.

    ``global_batch``: the user's actual (global) batch size.  The
    batch dim shards over data x fsdp, so candidates whose
    data x fsdp does not divide it are unusable — they are filtered
    out rather than discovered as a device_put error at the first
    step.

    ``tune_space`` (dry-run mode only): Strategy-field value lists,
    e.g. ``{"num_micro_steps": [1, 2, 4], "remat": ["dots", "full"]}``
    — after the mesh race picks a winner, Bayesian optimization
    (``bayes_search.tune_strategy``) spends ``tune_budget`` extra
    timed builds searching the tunables inside it.
    """
    if devices is None:
        devices = jax.devices()
    profile = analyse_model(init_params_fn, optimizer)
    timings = {}

    planner = None
    if load_strategy is not None:
        strategy = load_strategy
        # elastic re-mesh: a pinned strategy sized for the PREVIOUS
        # world is structurally illegal after a membership change
        # (its mesh product no longer matches the device count) —
        # re-solve the factorization for the new world instead of
        # failing at mesh creation.  The agent exports
        # DLROVER_TPU_PREV_WORLD across restarts; a same-size restart
        # keeps the pinned strategy untouched.
        from dlrover_tpu.accelerate.solver import (
            resolve_for_world,
            strategy_device_count,
        )

        if strategy_device_count(strategy) != len(devices):
            plan = resolve_for_world(
                profile,
                len(devices),
                batch_per_replica,
                seq_len,
                prior=strategy,
                long_context=long_context,
                global_batch=global_batch,
            )
            strategy = plan.strategy
    else:
        candidates = generate_candidates(
            profile,
            len(devices),
            long_context=long_context,
            moe=moe,
            batch_per_replica=batch_per_replica,
            seq_len=seq_len,
            global_batch=global_batch,
        )
        if not candidates:
            raise RuntimeError(
                f"no strategy fits: {profile.num_params} params on "
                f"{len(devices)} devices"
            )
        if dry_run and sample_batch_fn is not None:
            def build(s):
                fns, _, _ = _build_for_strategy(
                    s, loss_fn, optimizer, init_params_fn,
                    param_axes, devices,
                )
                state = fns.init_state(jax.random.PRNGKey(0))
                batch = sample_batch_fn(fns.batch_sharding)
                return fns.train_step, state, batch

            from dlrover_tpu.accelerate.search import successive_halving

            strategy, timings = successive_halving(build, candidates)
            if strategy is None:
                strategy = candidates[0]
            elif tune_space:
                # BO over the winner's tunables (micro steps, remat,
                # pipe microbatches, ...) — the knobs no analytic
                # model predicts
                from dlrover_tpu.accelerate.bayes_search import (
                    tune_strategy,
                )

                strategy, tune_hist = tune_strategy(
                    build, strategy, tune_space, budget=tune_budget
                )
                timings["bayes_tune"] = tune_hist
            # calibrate the per-term cost model on what was measured:
            # result.planner.plan(n) ranks candidates at target scale
            from dlrover_tpu.accelerate.dim_planner import (
                CalibratedPlanner,
            )

            by_desc = {c.describe(): c for c in candidates}
            measured = [
                (by_desc[d], t[-1])
                for d, t in timings.items()
                if d in by_desc and t and t[-1] is not None
            ]
            # same constant rank basis as candidate generation: with
            # a known global batch, per-device tokens = global/n
            rank_bpr = (
                global_batch / len(devices)
                if global_batch is not None
                else batch_per_replica
            )
            planner = CalibratedPlanner(
                profile,
                batch_per_replica=rank_bpr,
                seq_len=seq_len,
            )
            planner.calibrate(measured)
        else:
            strategy = candidates[0]

    logger.info(
        "auto_accelerate: %s params -> strategy %s",
        profile.num_params,
        strategy.describe(),
    )
    fns, mesh_ctx, rules = _build_for_strategy(
        strategy, loss_fn, optimizer, init_params_fn, param_axes, devices
    )
    return AccelerateResult(
        fns=fns,
        strategy=strategy,
        mesh_ctx=mesh_ctx,
        rules=rules,
        profile=profile,
        timings=timings,
        planner=planner,
    )
