"""Measurement-calibrated mesh-dimension planner.

Reference parity: the profile-driven shard planners
(``atorch/atorch/auto/auto/shard_planners/dim_planner.py:238`` — device
mesh dims from profiling + dynamic programming — and the MIP TP planner
``mip_tp_planner.py:496``).  The reference profiles ops on a few GPUs,
then solves for the mesh shape to use at full scale.

The TPU translation: the strategy space is mesh factorizations whose
step time decomposes into a handful of physical terms (compute shard,
grad reduce, FSDP gathers, TP activation reductions, pipe bubble,
SP/EP hops — the same terms ``strategy.estimate_step_cost`` ranks by
analytically).  Instead of an ILP over an op graph (GSPMD already does
op-level placement), the planner:

1. expresses every candidate as a FEATURE VECTOR of those terms,
2. CALIBRATES per-term coefficients from a few timed dry runs at
   whatever scale is actually available (ridge regression toward the
   analytic prior — small-sample-safe),
3. ranks the full candidate space AT THE TARGET device count with the
   calibrated model — extrapolating measurements from an 8-device
   profile run to a 256-chip plan, which is exactly the reference
   planner's job.
"""

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from dlrover_tpu.accelerate.analyser import ModelProfile
from dlrover_tpu.accelerate.strategy import (
    FEATURE_NAMES,
    Strategy,
    generate_candidates,
    strategy_cost_terms,
)


def strategy_features(
    s: Strategy,
    profile: ModelProfile,
    batch_per_replica: int = 1,
    seq_len: int = 2048,
) -> np.ndarray:
    """Per-term second estimates (the analytic model of
    ``estimate_step_cost`` split into its addends); the calibrated
    planner learns a scale for each."""
    return np.asarray(
        strategy_cost_terms(s, profile, batch_per_replica, seq_len)
    )


@dataclass
class CalibratedPlanner:
    """Fit per-term coefficients from measured (strategy, step-time)
    pairs, then rank candidates — including at a DIFFERENT (larger)
    device count than the measurements were taken at."""

    profile: ModelProfile
    batch_per_replica: int = 1
    seq_len: int = 2048
    ridge: float = 1e-2

    def __post_init__(self):
        # analytic prior: every term at its modeled scale (weight 1)
        self.weights = np.ones(len(FEATURE_NAMES))

    def _features(self, s: Strategy) -> np.ndarray:
        return strategy_features(
            s, self.profile, self.batch_per_replica, self.seq_len
        )

    def calibrate(
        self, measurements: Sequence[Tuple[Strategy, float]]
    ) -> np.ndarray:
        """Ridge regression of measured step seconds onto the feature
        terms, shrunk toward the analytic prior (weight 1): with 2-3
        measurements most terms are unobserved and keep their prior;
        observed terms get rescaled by reality (e.g. an ICI link that
        delivers half the modeled bandwidth doubles its comm weights).
        Returns the fitted weights (also stored on self)."""
        meas = [
            (s, t) for s, t in measurements
            if t is not None and np.isfinite(t)
        ]
        if not meas:
            return self.weights
        F = np.stack([self._features(s) for s, _ in meas])
        y = np.array([t for _, t in meas])
        # column scaling so ridge strength is comparable across terms
        scale = np.maximum(np.abs(F).max(axis=0), 1e-12)
        Fn = F / scale
        lam = self.ridge * len(meas)
        # scaled weights ws = w * scale; prior w=1 -> ws = scale
        A = Fn.T @ Fn + lam * np.eye(F.shape[1])
        b = Fn.T @ y + lam * scale
        w_scaled = np.linalg.solve(A, b)
        self.weights = np.clip(w_scaled / scale, 0.0, None)
        return self.weights

    def predict(self, s: Strategy) -> float:
        return float(self._features(s) @ self.weights)

    def rank(
        self, candidates: Sequence[Strategy]
    ) -> List[Tuple[Strategy, float]]:
        scored = [(s, self.predict(s)) for s in candidates]
        scored.sort(key=lambda sv: sv[1])
        return scored

    def plan(
        self,
        n_devices: int,
        max_tensor: int = 8,
        long_context: bool = False,
        moe: bool = False,
        top_k: int = 5,
    ) -> List[Tuple[Strategy, float]]:
        """Candidate plans for ``n_devices`` (possibly >> the measured
        scale), ranked by the calibrated model — the reference dim
        planner's profile-small/plan-big flow."""
        cands = generate_candidates(
            self.profile,
            n_devices,
            max_tensor=max_tensor,
            long_context=long_context,
            moe=moe,
            batch_per_replica=self.batch_per_replica,
            seq_len=self.seq_len,
        )
        return self.rank(cands)[:top_k]
