"""Strategy representation + candidate generation.

Reference parity: ``atorch/atorch/auto/strategy.py:4`` (``Strategy`` =
ordered opt-method list), ``auto/engine/optimization_method.py``
(candidate generation) and the semi-auto ``load_strategy`` path of
``auto_accelerate`` (``auto/accelerate.py:406``).

A TPU strategy is fully described by (mesh dims, rule flags, remat,
micro-steps) — there is no module surgery; candidates are mesh
factorizations that pass the memory-fit model, ranked by a simple
cost model and optionally re-ranked by a timed dry run.
"""

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.accelerate.analyser import ModelProfile, fits_in_memory
from dlrover_tpu.parallel.mesh import AxisName


@dataclass(frozen=True)
class Strategy:
    """One parallelization plan."""

    data: int = 1
    fsdp: int = 1
    tensor: int = 1
    seq: int = 1
    expert: int = 1
    pipe: int = 1
    remat: str = "full"
    num_micro_steps: int = 1
    # GPipe microbatch count when pipe > 1 (0 -> auto: 2 x pipe)
    pipe_microbatches: int = 0
    extras: Tuple = ()

    @property
    def n_devices(self) -> int:
        return (
            self.data
            * self.fsdp
            * self.tensor
            * self.seq
            * self.expert
            * self.pipe
        )

    def mesh_dims(self) -> List[Tuple[str, int]]:
        return [
            (AxisName.PIPELINE, self.pipe),
            (AxisName.DATA, self.data),
            (AxisName.FSDP, self.fsdp),
            (AxisName.EXPERT, self.expert),
            (AxisName.SEQUENCE, self.seq),
            (AxisName.TENSOR, self.tensor),
        ]

    def rule_flags(self) -> Dict[str, bool]:
        return {
            "fsdp": self.fsdp > 1,
            "tensor_parallel": self.tensor > 1,
            "sequence_parallel": self.seq > 1,
            "expert_parallel": self.expert > 1,
            "pipeline": self.pipe > 1,
        }

    def describe(self) -> str:
        parts = [
            f"{k}={v}"
            for k, v in [
                ("dp", self.data),
                ("fsdp", self.fsdp),
                ("tp", self.tensor),
                ("sp", self.seq),
                ("ep", self.expert),
                ("pp", self.pipe),
            ]
            if v > 1
        ]
        return "x".join(parts) if parts else "single-device"


def load_strategy(config: Dict) -> Strategy:
    """Semi-auto: user supplies the plan (reference ``load_strategy``)."""
    return Strategy(**config)


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


# coarse v5e-class hardware constants for RANKING (not prediction):
# only the ordering of candidates matters, so absolute calibration is
# irrelevant as long as the compute/comm ratio is in the right regime
_PEAK_FLOPS = 197e12
_ICI_BW = 4.5e10  # bytes/sec one direction, per link


# ordered addends of the step-cost model; the calibrated dim planner
# (accelerate/dim_planner.py) fits a per-term coefficient to each
FEATURE_NAMES = (
    "compute",
    "dp_reduce",
    "fsdp_gather",
    "tp_reduce",
    "pipe_hop",
    "sp_hop",
    "ep_hop",
)


def strategy_cost_terms(
    s: Strategy,
    profile: ModelProfile,
    batch_per_replica: int = 1,
    seq_len: int = 2048,
) -> List[float]:
    """Per-term second estimates, ordered as ``FEATURE_NAMES``:

    - compute: 6N FLOPs/token shard, scaled by the GPipe bubble
      (1 + (P-1)/M) when pipe > 1
    - DP/FSDP grad reduce: ~2x grad bytes over ICI when dp*fsdp > 1
    - FSDP param all-gathers: ~2x param bytes more (fwd + bwd)
    - TP: per-layer activation reductions (4 per layer, bf16)
    - pipe: stage-boundary activation hops (every microbatch crosses
      P-1 boundaries forward and backward)
    - seq/expert: all-to-all / ring hops on activations
    """
    # fixed global token count (pure-DP framing: per-device batch x
    # devices); any constant works — only the ordering matters
    global_tokens = batch_per_replica * seq_len * max(s.n_devices, 1)
    model_shard = max(s.tensor * s.pipe, 1)
    compute = (
        6.0 * profile.num_params * global_tokens
        / max(s.n_devices, 1) / _PEAK_FLOPS
    )
    if s.pipe > 1:
        micro = s.pipe_microbatches or 2 * s.pipe
        compute *= 1.0 + (s.pipe - 1) / max(micro, 1)
    tokens = batch_per_replica * seq_len  # per-device activation traffic

    grad_bytes = profile.num_params * 4.0 / model_shard
    # one layer-boundary activation tensor [tokens, hidden] in bf16:
    # the whole-model census is ~7 live tensors per layer, so divide
    # it back out; floor at a 1k-hidden model
    hidden_bytes = max(
        profile.activation_bytes_per_sample
        / max(seq_len, 1) / max(profile.num_layers, 1) / 7.0,
        2.0 * 1024,
    )
    act_bytes = tokens * hidden_bytes

    terms = [compute, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0]
    if s.data * s.fsdp > 1:
        terms[1] = 2.0 * grad_bytes / _ICI_BW
    if s.fsdp > 1:
        terms[2] = 2.0 * profile.num_params * 4.0 / model_shard / _ICI_BW
    if s.tensor > 1:
        terms[3] = 4.0 * max(profile.num_layers, 1) * act_bytes / _ICI_BW
    if s.pipe > 1:
        terms[4] = 4.0 * (s.pipe - 1) / s.pipe * act_bytes / _ICI_BW
    if s.seq > 1:
        terms[5] = 2.0 * s.seq * act_bytes / _ICI_BW
    if s.expert > 1:
        terms[6] = 2.0 * act_bytes / _ICI_BW
    return terms


def estimate_step_cost(
    s: Strategy,
    profile: ModelProfile,
    batch_per_replica: int = 1,
    seq_len: int = 2048,
) -> float:
    """Relative per-step wall-clock estimate for ranking candidates
    (reference role: the Brain's throughput model + the MIP planner's
    objective, ``mip_tp_planner.py:496``, collapsed to the terms that
    matter on a TPU mesh — see :func:`strategy_cost_terms`).

    Configs are compared at a FIXED global batch (the user's effective
    batch): per-token compute is then identical across factorizations
    (6N/n_devices per device), so the ranking is decided by what each
    strategy ADDS."""
    return float(
        sum(strategy_cost_terms(s, profile, batch_per_replica, seq_len))
    )


def generate_candidates(
    profile: ModelProfile,
    n_devices: int,
    max_tensor: int = 8,
    long_context: bool = False,
    moe: bool = False,
    batch_per_replica: int = 1,
    seq_len: int = 2048,
    global_batch: Optional[int] = None,
) -> List[Strategy]:
    """Mesh factorizations that fit memory, ranked by the workload
    cost model (:func:`estimate_step_cost` — compute shard + grad
    reduce + FSDP gathers + TP reductions + pipe bubble, evaluated at
    the actual batch/seq).

    A factorization whose activations overflow at micro_steps=1 is
    retried with gradient accumulation (2/4/8 micro steps) — the
    reference searches micro-batching as part of the strategy space,
    not as a user afterthought.

    With ``global_batch`` set, factorizations whose batch sharding
    (data x fsdp) doesn't divide it are dropped (they'd fail at the
    first ``device_put``) and each candidate's MEMORY fit is evaluated
    at ITS OWN per-device batch (``global_batch / (data*fsdp)``).
    The cost RANKING keeps a
    constant per-device basis (``global_batch / n_devices``): the
    model's compute term assumes a fixed global batch, and feeding
    each candidate its own bpd would charge model-parallel plans
    tensor*pipe-times the compute of data-parallel ones."""
    if global_batch is not None and global_batch < 1:
        raise ValueError(
            f"global_batch must be >= 1, got {global_batch}"
        )
    rank_bpr = (
        global_batch / n_devices
        if global_batch is not None
        else batch_per_replica
    )
    candidates = []
    for tensor, fsdp_d, pipe in itertools.product(
        _divisors(n_devices), _divisors(n_devices), (1, 2, 4)
    ):
        if tensor > max_tensor:
            continue
        if n_devices % (tensor * fsdp_d * pipe) != 0:
            continue
        if pipe > 1 and (
            profile.num_layers == 0 or profile.num_layers % pipe != 0
        ):
            # stage dim must split a detected layer stack evenly; with
            # no stack (num_layers=0) the LAYERS->PIPELINE rule shards
            # nothing, so the pipe memory fold would be fictitious
            continue
        rest = n_devices // (tensor * fsdp_d * pipe)
        seq = 1
        expert = 1
        if long_context and rest % 2 == 0 and rest > 1:
            seq = 2
            rest //= 2
        if moe and rest % 2 == 0 and rest > 1:
            expert = 2
            rest //= 2
        batch_shard = rest * fsdp_d  # batch dim shards over data x fsdp
        if global_batch is not None:
            if global_batch % batch_shard != 0:
                continue  # would fail at the first device_put
            bpd = global_batch // batch_shard
        else:
            bpd = batch_per_replica
        for micro in (1, 2, 4, 8):
            # micro | bpd also guarantees the accumulation reshape's
            # global divisibility: global = bpd * batch_shard
            if micro > 1 and bpd % micro != 0:
                continue
            fits, util = fits_in_memory(
                profile,
                n_devices,
                fsdp=fsdp_d,
                tensor=tensor,
                batch_per_device=bpd,
                pipe=pipe,
                micro_steps=micro,
            )
            if fits:
                s = Strategy(
                    data=rest,
                    fsdp=fsdp_d,
                    tensor=tensor,
                    seq=seq,
                    expert=expert,
                    pipe=pipe,
                    num_micro_steps=micro,
                )
                candidates.append((s, util))
                break  # smallest micro count that fits wins

    # rank by modeled step time at the CONSTANT per-device basis
    # rank_bpr = global_batch / n_devices (memory fit above used each
    # candidate's own per-device batch); ranking at per-candidate
    # batches would charge model-parallel plans tensor*pipe-times the
    # compute of data-parallel ones — see
    # test_global_batch_keeps_model_parallel_competitive.  Memory
    # utilization breaks ties.
    candidates.sort(
        key=lambda su: (
            estimate_step_cost(su[0], profile, rank_bpr, seq_len),
            su[1],
        )
    )
    seen = set()
    unique = []
    for s, _ in candidates:
        key = (s.data, s.fsdp, s.tensor, s.seq, s.expert, s.pipe)
        if key not in seen:
            seen.add(key)
            unique.append(s)
    return unique
