"""Strategy representation + candidate generation.

Reference parity: ``atorch/atorch/auto/strategy.py:4`` (``Strategy`` =
ordered opt-method list), ``auto/engine/optimization_method.py``
(candidate generation) and the semi-auto ``load_strategy`` path of
``auto_accelerate`` (``auto/accelerate.py:406``).

A TPU strategy is fully described by (mesh dims, rule flags, remat,
micro-steps) — there is no module surgery; candidates are mesh
factorizations that pass the memory-fit model, ranked by a simple
cost model and optionally re-ranked by a timed dry run.
"""

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.accelerate.analyser import ModelProfile, fits_in_memory
from dlrover_tpu.parallel.mesh import AxisName


@dataclass(frozen=True)
class Strategy:
    """One parallelization plan."""

    data: int = 1
    fsdp: int = 1
    tensor: int = 1
    seq: int = 1
    expert: int = 1
    pipe: int = 1
    remat: str = "full"
    num_micro_steps: int = 1
    # GPipe microbatch count when pipe > 1 (0 -> auto: 2 x pipe)
    pipe_microbatches: int = 0
    extras: Tuple = ()

    @property
    def n_devices(self) -> int:
        return (
            self.data
            * self.fsdp
            * self.tensor
            * self.seq
            * self.expert
            * self.pipe
        )

    def mesh_dims(self) -> List[Tuple[str, int]]:
        return [
            (AxisName.PIPELINE, self.pipe),
            (AxisName.DATA, self.data),
            (AxisName.FSDP, self.fsdp),
            (AxisName.EXPERT, self.expert),
            (AxisName.SEQUENCE, self.seq),
            (AxisName.TENSOR, self.tensor),
        ]

    def rule_flags(self) -> Dict[str, bool]:
        return {
            "fsdp": self.fsdp > 1,
            "tensor_parallel": self.tensor > 1,
            "sequence_parallel": self.seq > 1,
            "expert_parallel": self.expert > 1,
            "pipeline": self.pipe > 1,
        }

    def describe(self) -> str:
        parts = [
            f"{k}={v}"
            for k, v in [
                ("dp", self.data),
                ("fsdp", self.fsdp),
                ("tp", self.tensor),
                ("sp", self.seq),
                ("ep", self.expert),
                ("pp", self.pipe),
            ]
            if v > 1
        ]
        return "x".join(parts) if parts else "single-device"


def load_strategy(config: Dict) -> Strategy:
    """Semi-auto: user supplies the plan (reference ``load_strategy``)."""
    return Strategy(**config)


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def generate_candidates(
    profile: ModelProfile,
    n_devices: int,
    max_tensor: int = 8,
    long_context: bool = False,
    moe: bool = False,
    batch_per_replica: int = 1,
) -> List[Strategy]:
    """Mesh factorizations that fit memory, cheapest-communication
    first (DP > FSDP > TP in preference — TP pays per-layer
    collectives, FSDP pays per-step gathers, DP only grad reduce)."""
    candidates = []
    for tensor, fsdp_d, pipe in itertools.product(
        _divisors(n_devices), _divisors(n_devices), (1, 2, 4)
    ):
        if tensor > max_tensor:
            continue
        if n_devices % (tensor * fsdp_d * pipe) != 0:
            continue
        if pipe > 1 and (
            profile.num_layers == 0 or profile.num_layers % pipe != 0
        ):
            # stage dim must split a detected layer stack evenly; with
            # no stack (num_layers=0) the LAYERS->PIPELINE rule shards
            # nothing, so the pipe memory fold would be fictitious
            continue
        rest = n_devices // (tensor * fsdp_d * pipe)
        seq = 1
        expert = 1
        if long_context and rest % 2 == 0 and rest > 1:
            seq = 2
            rest //= 2
        if moe and rest % 2 == 0 and rest > 1:
            expert = 2
            rest //= 2
        s = Strategy(
            data=rest,
            fsdp=fsdp_d,
            tensor=tensor,
            seq=seq,
            expert=expert,
            pipe=pipe,
        )
        fits, util = fits_in_memory(
            profile,
            n_devices,
            fsdp=fsdp_d,
            tensor=tensor,
            batch_per_device=batch_per_replica,
            pipe=pipe,
        )
        if fits:
            candidates.append((s, util))
    # rank: prefer less model-parallelism (pipe pays the bubble, TP
    # pays per-layer collectives, FSDP per-step gathers, DP only the
    # grad reduce), then lower memory pressure
    candidates.sort(
        key=lambda su: (su[0].pipe, su[0].tensor, su[0].fsdp, su[1])
    )
    seen = set()
    unique = []
    for s, _ in candidates:
        key = (s.data, s.fsdp, s.tensor, s.seq, s.expert, s.pipe)
        if key not in seen:
            seen.add(key)
            unique.append(s)
    return unique
