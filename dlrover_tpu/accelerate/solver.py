"""Joint constraint solver over (mesh × remat × microbatch × tiles).

Reference parity: ``atorch/atorch/auto/opt_lib/shard_planners/
mip_tp_planner.py:496`` — a MIP over operator placement + resource
constraints.  On TPU, GSPMD already solves op placement, so the joint
decision that remains is the one the bench is hand-tuned over today:

    mesh factorization × remat policy × micro-batch count
    × flash-attention tile shape

under a per-device HBM model and a VMEM model for the kernel tiles.
The space is tiny (≈10^3–10^4 points after pruning), so the "MIP" is
an exact pruned-exhaustive solve — deterministic, dependency-free, and
auditable, which a real ILP encoding of the same objective would not
be.  The objective reuses the calibrated per-term cost model
(``dim_planner.CalibratedPlanner`` fits its coefficients from timed
dry runs), extended with remat recompute and an attention HBM-traffic
term, so measurements improve the solve the same way they improve
plain ranking.

Validation anchor (tests + chip): for the v5e bench workload
(llama-0.6b, batch 8, seq 2048, one 16 GB chip) the solver must
reproduce the measured-best hand tuning from its model alone:
``remat=dots`` (none does not fit, full recomputes more), micro=1,
flash tiles 1024×512 (block_q = seq/2 keeps ≥2 pipeline steps per
(batch, head) grid row; block_kv = block_q/2 halves the bwd
accumulation conflict window; both bounded by VMEM).
"""

import dataclasses
import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from dlrover_tpu.accelerate.analyser import (
    ModelProfile,
    device_memory_bytes,
)
from dlrover_tpu.accelerate.strategy import (
    Strategy,
    generate_candidates,
    strategy_cost_terms,
)

# remat policy -> (retained-activation fraction, step-FLOP multiplier).
# fwd:bwd ≈ 1:2; full remat re-runs the forward (+1/3 of step FLOPs),
# "dots" recomputes only matmul/attention internals (~half the fwd)
# while keeping ~35% of activation bytes resident (norms + boundaries).
REMAT_POLICIES: Dict[str, Tuple[float, float]] = {
    "none": (1.00, 1.0),
    "dots": (0.35, 1.0 + 1.0 / 6.0),
    "full": (0.08, 1.0 + 1.0 / 3.0),
}

# v5e-class VMEM budget available to one kernel's working set (the
# hardware has ~128 MiB; Mosaic reserves space for double buffering
# and spills — beyond ~half, compilation degrades or fails)
DEFAULT_VMEM_BUDGET = 64 * (1 << 20)


@dataclass(frozen=True)
class JointPlan:
    """One point of the joint space (what the bench hand-tunes)."""

    strategy: Strategy
    remat: str
    block_q: int
    block_kv: int
    predicted_step_s: float
    memory_utilization: float

    def describe(self) -> Dict:
        return {
            "mesh": {
                "data": self.strategy.data,
                "fsdp": self.strategy.fsdp,
                "tensor": self.strategy.tensor,
                "seq": self.strategy.seq,
                "expert": self.strategy.expert,
                "pipe": self.strategy.pipe,
            },
            "micro_steps": self.strategy.num_micro_steps,
            "remat": self.remat,
            "flash_tiles": [self.block_q, self.block_kv],
            "predicted_step_s": round(self.predicted_step_s, 4),
            "memory_utilization": round(self.memory_utilization, 3),
        }


@dataclass(frozen=True)
class OffloadGroupPlan:
    """Grouped-backward plan for the host-offload path: how many
    backward passes per step (``n_groups``) and where the stacked
    layer dim splits (``boundaries`` — the ``init_ngrouped_params``
    input)."""

    n_groups: int
    boundaries: Tuple[int, ...]
    group_params: Tuple[int, ...]
    predicted_peak_bytes: int
    budget_bytes: int

    def describe(self) -> Dict:
        return {
            "n_groups": self.n_groups,
            "boundaries": list(self.boundaries),
            "group_params_m": [
                round(p / 1e6, 1) for p in self.group_params
            ],
            "predicted_peak_gb": round(
                self.predicted_peak_bytes / 1e9, 2
            ),
            "budget_gb": round(self.budget_bytes / 1e9, 2),
        }


def balanced_boundaries(
    layer_params: Sequence[int],
    n_groups: int,
    embed_params: int = 0,
    head_params: int = 0,
) -> Tuple[int, ...]:
    """Layer split points giving ``n_groups`` contiguous segments of
    near-equal parameter weight.  ``embed_params``/``head_params``
    are charged to the first/last layer (group 0 carries the
    embedding, the last group the lm head — the
    ``loss_fn_ngrouped`` contract), so a heavy head pushes the last
    boundary earlier instead of silently unbalancing the tail
    group.  Handles odd (non-divisible) layer counts; every group
    keeps at least one layer."""
    n_layers = len(layer_params)
    if not 1 <= n_groups <= n_layers:
        raise ValueError(
            f"cannot split {n_layers} layers into {n_groups} groups"
        )
    weights = [float(w) for w in layer_params]
    weights[0] += float(embed_params)
    weights[-1] += float(head_params)
    total = sum(weights)
    cum = [0.0]
    for w in weights:
        cum.append(cum[-1] + w)
    bounds: List[int] = []
    prev = 0
    for k in range(1, n_groups):
        target = total * k / n_groups
        lo = prev + 1
        hi = n_layers - (n_groups - k)  # leave >=1 layer per group
        best = min(
            range(lo, hi + 1), key=lambda b: abs(cum[b] - target)
        )
        bounds.append(best)
        prev = best
    return tuple(bounds)


def solve_offload_groups(
    profile: ModelProfile,
    batch_per_replica: int = 1,
    remat: str = "full",
    headroom: float = 0.85,
    max_groups: int = 8,
    hbm_bytes: Optional[int] = None,
    layer_params: Optional[Sequence[int]] = None,
    embed_params: int = 0,
    head_params: int = 0,
    transient_bytes: int = 768 << 20,
) -> OffloadGroupPlan:
    """Pick the grouped-backward split for the host-offload path.

    The offloaded step's HBM peak is ``bf16 params + retained
    activations + ONE group's bf16 dW tree + the chunk-stream
    transient`` — the dW term is the only one N shrinks, so the solve
    is: smallest N whose balanced split fits the budget (every extra
    group costs a full extra backward pass, so more groups than
    needed is pure slowdown).  ``layer_params`` is the per-layer
    parameter count (uniform split of the stacked params by default);
    ``embed_params``/``head_params`` weight the first/last groups the
    way ``loss_fn_ngrouped`` assigns the unstacked leaves.  Raises
    ``ValueError`` when even ``max_groups`` does not fit."""
    if remat not in REMAT_POLICIES:
        raise ValueError(f"unknown remat policy {remat!r}")
    budget = float(hbm_bytes or device_memory_bytes()) * headroom
    act_frac = REMAT_POLICIES[remat][0]
    acts = (
        profile.activation_bytes_per_sample
        * batch_per_replica
        * act_frac
    )
    n_layers = max(profile.num_layers, 1)
    if layer_params is None:
        stacked = max(
            profile.num_params - embed_params - head_params, 0
        )
        layer_params = [stacked / n_layers] * n_layers
    resident = 2.0 * profile.num_params + acts + transient_bytes
    peak = None
    for n in range(1, min(max_groups, len(layer_params)) + 1):
        bounds = balanced_boundaries(
            layer_params, n, embed_params, head_params
        )
        edges = [0, *bounds, len(layer_params)]
        group_params = []
        for lo, hi in zip(edges, edges[1:]):
            w = sum(layer_params[lo:hi])
            if lo == 0:
                w += embed_params
            if hi == len(layer_params):
                w += head_params
            group_params.append(int(w))
        peak = resident + 2.0 * max(group_params)
        if peak <= budget:
            return OffloadGroupPlan(
                n_groups=n,
                boundaries=bounds,
                group_params=tuple(group_params),
                predicted_peak_bytes=int(peak),
                budget_bytes=int(budget),
            )
    raise ValueError(
        f"no grouped split fits: {max_groups} groups still need "
        f"{(peak or resident) / 1e9:.2f} GB of "
        f"{budget / 1e9:.2f} GB"
    )


def strategy_device_count(strategy: Strategy) -> int:
    """Devices a strategy's mesh factorization consumes."""
    return (
        strategy.data
        * strategy.fsdp
        * strategy.tensor
        * strategy.seq
        * strategy.expert
        * strategy.pipe
    )


def resolve_for_world(
    profile: ModelProfile,
    n_devices: int,
    batch_per_replica: int,
    seq_len: int,
    prior: Optional[Strategy] = None,
    **solve_kwargs,
) -> JointPlan:
    """Re-solve the parallelism strategy for a CHANGED device count
    (the elastic re-mesh: a preempted host shrank the world, or a
    replacement grew it back).

    The prior strategy's tunables are preserved where still legal —
    the calibration that picked them came from measurements of this
    very workload — but the mesh factorization is re-solved from
    scratch: a strategy sized for 8 hosts is structurally illegal on
    4 (its mesh product no longer matches), and even legal survivors
    may be far from optimal at the new scale.  Returns the best
    :class:`JointPlan` for the new world; raises ``ValueError`` when
    nothing fits (the job genuinely cannot run at this size — better
    a loud scheduling failure than an OOM loop)."""
    if prior is not None:
        solve_kwargs.setdefault(
            "pipe_microbatches", prior.pipe_microbatches
        )
        if prior.expert > 1:
            solve_kwargs.setdefault("moe", True)
    plans = solve(
        profile,
        n_devices,
        batch_per_replica,
        seq_len,
        top_k=1,
        **solve_kwargs,
    )
    best = plans[0]
    if prior is not None:
        from dlrover_tpu.common.log import default_logger

        default_logger.info(
            "world change re-solve: %s devices -> mesh %s (was "
            "data=%s fsdp=%s tensor=%s seq=%s expert=%s pipe=%s "
            "for %s devices)",
            n_devices, best.describe()["mesh"], prior.data,
            prior.fsdp, prior.tensor, prior.seq, prior.expert,
            prior.pipe, strategy_device_count(prior),
        )
    return best


def candidate_tiles(
    seq_len: int,
    head_dim: int = 128,
    vmem_budget: int = DEFAULT_VMEM_BUDGET,
) -> List[Tuple[int, int]]:
    """Feasible (block_q, block_kv) pairs.

    Constraints (each encodes a hardware fact, not a fit-to-answer):
    - blocks are multiples of 128 covering the sequence evenly;
    - ≥2 q-blocks per (batch, head) grid row: with one q block the
      kernel's KV stream cannot overlap the next row's prologue
      (block_q ≤ seq/2) — validated on chip: forcing 2048×512 at
      seq 2048 measures 0.521 MFU vs 0.530 at 1024×512 (r4);
    - bwd VMEM working set fits the budget: two bq×bk f32 score/
      dscore tiles + ~7 tile×head_dim f32 operands (q, k, v, o, do,
      dq, partial dk/dv);
    - block_kv ≤ block_q/2 (when blocks are big enough to halve):
      the bwd accumulates dk/dv across the whole q loop, so each kv
      block's accumulator stays live for the full pass — halving the
      kv block halves that conflict window, measured faster on v5e
      than square tiles at every size ≥256 (r3 tile sweep).
    """
    sizes = [s for s in (128, 256, 512, 1024, 2048) if s <= seq_len]
    out = []
    for bq, bk in itertools.product(sizes, sizes):
        if seq_len % bq or seq_len % bk:
            continue
        if seq_len >= 256 and seq_len // bq < 2:
            continue
        if bk > max(bq // 2, 128):
            continue
        scores = 2 * bq * bk * 4
        operands = 4 * (5 * bq * head_dim + 2 * bk * head_dim)
        if scores + operands > vmem_budget:
            continue
        out.append((bq, bk))
    return out


def attention_traffic_s(
    bq: int,
    bk: int,
    batch: int,
    seq_len: int,
    n_heads: int,
    n_layers: int,
    head_dim: int = 128,
    hbm_gbps: float = 800.0,
) -> float:
    """HBM seconds spent re-streaming K/V per step: every q block
    reads the (causal) half of the KV sequence, so traffic scales
    with seq/bq; the bwd re-streams similarly with roles swapped
    (seq/bk).  This is the term that makes tiny tiles slow."""
    kv_bytes = 2 * seq_len * head_dim * 2  # K+V, bf16
    q_passes = seq_len / bq  # fwd: each q block streams ~S/2 of KV
    kv_passes = seq_len / bk  # bwd: each kv block streams the q side
    per_head = kv_bytes * 0.5 * (q_passes + kv_passes)
    total = per_head * n_heads * batch * n_layers
    return total / (hbm_gbps * 1e9)


def solve(
    profile: ModelProfile,
    n_devices: int,
    batch_per_replica: int,
    seq_len: int,
    n_heads: int = 16,
    head_dim: int = 128,
    global_batch: Optional[int] = None,
    long_context: bool = False,
    moe: bool = False,
    weights: Optional[Sequence[float]] = None,
    vmem_budget: int = DEFAULT_VMEM_BUDGET,
    headroom: float = 0.85,
    top_k: int = 5,
    pipe_microbatches: int = 0,
) -> List[JointPlan]:
    """Exact solve over the pruned joint space; best plan first.

    ``weights``: calibrated per-term coefficients from
    ``CalibratedPlanner.calibrate`` (None = analytic prior) — the
    solver and the measured calibration share one objective.

    ``pipe_microbatches``: the GPipe microbatch count the job will
    actually run (``MeshContext.pipeline_microbatches``); 0 keeps the
    module_replace auto default of ``2*pipe``.  The activation
    residency of pipe>1 candidates scales with it.
    """
    hbm = device_memory_bytes() * headroom
    w = (
        np.ones(7)
        if weights is None
        else np.asarray(weights, dtype=float)
    )
    # tile feasibility depends on the PER-DEVICE sequence: a
    # seq-sharded strategy's kernel sees seq_len / s.seq, and tiles
    # legal globally can violate the >=2-q-blocks rule locally
    tiles_by_seq: Dict[int, List[Tuple[int, int]]] = {}

    def tiles_for(local_seq: int) -> List[Tuple[int, int]]:
        if local_seq not in tiles_by_seq:
            tiles_by_seq[local_seq] = candidate_tiles(
                local_seq, head_dim, vmem_budget
            )
        return tiles_by_seq[local_seq]

    if not tiles_for(seq_len):
        raise ValueError(
            f"no feasible flash tile for seq_len={seq_len} under "
            f"vmem_budget={vmem_budget}"
        )
    # mesh × micro candidates from the shared generator.  Its internal
    # memory gate assumes FULL resident activations (remat=none); a
    # profile copy with activations scaled to the strongest remat
    # keeps remat-rescuable candidates alive — the solver's own
    # per-policy gate below does the real pruning.
    min_act_frac = min(f for f, _ in REMAT_POLICIES.values())
    permissive = dataclasses.replace(
        profile,
        activation_bytes_per_sample=int(
            profile.activation_bytes_per_sample * min_act_frac
        ),
    )
    mesh_cands = generate_candidates(
        permissive,
        n_devices,
        long_context=long_context,
        moe=moe,
        batch_per_replica=batch_per_replica,
        seq_len=seq_len,
        global_batch=global_batch,
    )
    plans: List[JointPlan] = []
    expanded: List[Strategy] = []
    seen_keys = set()
    for s0 in mesh_cands:
        batch_shard = max(s0.data * s0.fsdp, 1)
        bpd0 = (
            global_batch // batch_shard
            if global_batch is not None
            else batch_per_replica
        )
        # the generator keeps only the SMALLEST fitting micro count;
        # the joint solve re-opens the micro axis — accumulation can
        # rescue a cheaper remat policy (none/dots) that the smallest
        # micro cannot hold
        for m in (1, 2, 4, 8):
            if m < s0.num_micro_steps or (m > 1 and bpd0 % m):
                continue
            s = dataclasses.replace(
                s0,
                num_micro_steps=m,
                # stamp the configured GPipe depth so the residency
                # estimate below tracks what the executor will run
                pipe_microbatches=(
                    pipe_microbatches
                    if s0.pipe > 1 and pipe_microbatches
                    else s0.pipe_microbatches
                ),
            )
            key = (
                s.data, s.fsdp, s.tensor, s.seq, s.expert, s.pipe,
                s.num_micro_steps, s.pipe_microbatches,
            )
            if key not in seen_keys:
                seen_keys.add(key)
                expanded.append(s)
    for s in expanded:
        shard = max(s.fsdp * s.tensor * s.pipe, 1)
        batch_shard = max(s.data * s.fsdp, 1)
        if global_batch is not None:
            bpd = global_batch // batch_shard
        else:
            bpd = batch_per_replica
        base_terms = np.asarray(
            strategy_cost_terms(
                s, profile, batch_per_replica, seq_len
            )
        )
        state = profile.train_state_bytes() / shard
        if s.num_micro_steps > 1:
            state += profile.num_params * 4.0 / shard
        full_acts = (
            profile.activation_bytes_per_sample
            * bpd
            / max(s.num_micro_steps, 1)
        )
        if s.pipe > 1:
            # chunked-1F1B pipeline executor (parallel/pipeline.py):
            # a stage holds only ITS layer shard's activations
            # (1/pipe) for a window of `pipe` in-flight microbatches
            # out of the num_mb-deep stream — residency is
            # act * (pipe/num_mb) * (1/pipe) = act/num_mb.  num_mb is
            # the strategy's ACTUAL microbatch count (0 = the
            # module_replace auto default of 2*pipe); hard-coding
            # 2*pipe made the memory estimate wrong by the ratio for
            # any other configured count.
            num_mb = s.pipe_microbatches or 2 * s.pipe
            full_acts /= float(num_mb)
        # accumulation is not free: every extra micro step re-reads
        # and re-writes the fp32 grad_sum (8 bytes/param over HBM) and
        # fragments the fused backward
        accum_s = (
            8.0
            * (profile.num_params / shard)
            * (s.num_micro_steps - 1)
            / (800.0 * 1e9)
        )
        for remat, (act_frac, flop_mult) in REMAT_POLICIES.items():
            used = state + full_acts * act_frac
            if used > hbm:
                continue
            terms = base_terms.copy()
            terms[0] *= flop_mult  # recompute lands on the compute term
            base_s = float(terms @ w) + accum_s
            local_seq = seq_len // max(s.seq, 1)
            # per-DEVICE attention traffic: heads shard over tensor,
            # layers over pipe, sequence over seq (charging unsharded
            # totals would overbill model-parallel plans by
            # tensor*pipe against the already-sharded compute terms)
            model_shard = max(s.tensor * s.pipe, 1)
            for bq, bk in tiles_for(local_seq):
                t = base_s + attention_traffic_s(
                    bq,
                    bk,
                    bpd,
                    local_seq,
                    n_heads,
                    profile.num_layers or 1,
                    head_dim,
                ) / model_shard
                plans.append(
                    JointPlan(
                        strategy=s,
                        remat=remat,
                        block_q=bq,
                        block_kv=bk,
                        predicted_step_s=t,
                        memory_utilization=used / hbm,
                    )
                )
    if not plans:
        raise ValueError(
            "no (mesh, remat, micro) point fits device memory"
        )
    plans.sort(key=lambda p: (p.predicted_step_s, p.memory_utilization))
    # one best tile/remat per strategy first, then runners-up: the
    # caller usually dry-runs the top few DISTINCT meshes
    seen = set()
    unique: List[JointPlan] = []
    rest: List[JointPlan] = []
    for p in plans:
        key = (
            p.strategy.data, p.strategy.fsdp, p.strategy.tensor,
            p.strategy.seq, p.strategy.expert, p.strategy.pipe,
            p.strategy.num_micro_steps,
        )
        if key in seen:
            rest.append(p)
        else:
            seen.add(key)
            unique.append(p)
    return (unique + rest)[:top_k]
