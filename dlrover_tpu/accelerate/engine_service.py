"""Strategy-generation service: acceleration decisions as an RPC.

Reference parity: ``atorch/atorch/auto/engine/acceleration_engine.py:13``
— the reference spawns a gRPC service (``engine/servicer.py`` +
``engine/client.py``) whose executor walks ANALYSE → candidate
generation → DRYRUN tasks so a whole cluster shares one strategy
brain.  The TPU form rides the same 2-RPC pickled-dataclass transport
the master uses (``common/comm.py``): a client submits a model profile
(abstract shapes — no weights cross the wire), the service answers
with ranked, memory-fit, workload-aware candidates; timed dry runs
stay client-side where the devices are (the reference's dry-run
workers are device-local too).
"""

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.accelerate.analyser import ModelProfile
from dlrover_tpu.accelerate.strategy import (
    Strategy,
    generate_candidates,
)
from dlrover_tpu.common.comm import MasterChannel, build_master_server
from dlrover_tpu.common.env import get_free_port
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.messages import (
    BoolResponse,
    Message,
    deserialize_message,
)


@dataclass
class StrategyRequest(Message):
    """Client -> service: the analysed model + workload shape."""

    num_params: int = 0
    param_bytes: int = 0
    optimizer_bytes: int = 0
    activation_bytes_per_sample: int = 0
    num_layers: int = 0
    n_devices: int = 1
    batch_per_replica: int = 1
    seq_len: int = 2048
    long_context: bool = False
    moe: bool = False
    max_candidates: int = 8


@dataclass
class StrategyResponse(Message):
    """Ranked candidates as Strategy kwargs dicts (wire-stable)."""

    candidates: List = field(default_factory=list)


def _strategy_to_dict(s: Strategy) -> Dict:
    # asdict stays exact as Strategy grows fields (a hand-rolled list
    # would silently drop e.g. pipe_microbatches on the wire)
    import dataclasses

    return dataclasses.asdict(s)


class StrategyService:
    """The in-process brain behind the RPC surface (usable directly —
    the service wrapper only adds the wire)."""

    def generate(self, req: StrategyRequest) -> StrategyResponse:
        profile = ModelProfile(
            num_params=req.num_params,
            param_bytes=req.param_bytes,
            largest_leaf=0,
            leaf_count=0,
            optimizer_bytes=req.optimizer_bytes,
            activation_bytes_per_sample=(
                req.activation_bytes_per_sample
            ),
            num_layers=req.num_layers,
        )
        cands = generate_candidates(
            profile,
            req.n_devices,
            long_context=req.long_context,
            moe=req.moe,
            batch_per_replica=req.batch_per_replica,
            seq_len=req.seq_len,
        )[: req.max_candidates]
        return StrategyResponse(
            candidates=[_strategy_to_dict(s) for s in cands]
        )


def start_strategy_service(
    port: int = 0,
) -> Tuple[object, int]:
    """Start the service; returns (grpc server, port)."""
    port = port or get_free_port()
    brain = StrategyService()

    def report_fn(envelope):
        return BoolResponse(success=True)

    def get_fn(envelope):
        req = deserialize_message(envelope.data)
        if isinstance(req, StrategyRequest):
            return brain.generate(req)
        return None

    server = build_master_server(port, report_fn, get_fn)
    server.start()
    logger.info("strategy service on port %d", port)
    return server, port


class StrategyClient:
    """Client side: profile in, ranked Strategy list out."""

    def __init__(self, addr: str):
        self._channel = MasterChannel(addr)

    def request_candidates(
        self,
        profile: ModelProfile,
        n_devices: int,
        batch_per_replica: int = 1,
        seq_len: int = 2048,
        long_context: bool = False,
        moe: bool = False,
    ) -> List[Strategy]:
        resp = self._channel.get(
            StrategyRequest(
                num_params=profile.num_params,
                param_bytes=profile.param_bytes,
                optimizer_bytes=profile.optimizer_bytes,
                activation_bytes_per_sample=(
                    profile.activation_bytes_per_sample
                ),
                num_layers=profile.num_layers,
                n_devices=n_devices,
                batch_per_replica=batch_per_replica,
                seq_len=seq_len,
                long_context=long_context,
                moe=moe,
            )
        )
        if resp is None:
            return []
        return [Strategy(**kw) for kw in resp.candidates]

    def close(self):
        self._channel.close()
