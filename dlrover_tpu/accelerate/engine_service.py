"""Strategy-generation service: acceleration decisions as an RPC.

Reference parity: ``atorch/atorch/auto/engine/acceleration_engine.py:13``
— the reference spawns a gRPC service (``engine/servicer.py`` +
``engine/client.py``) whose executor walks ANALYSE → candidate
generation → DRYRUN tasks so a whole cluster shares one strategy
brain.  The TPU form rides the same 2-RPC pickled-dataclass transport
the master uses (``common/comm.py``): a client submits a model profile
(abstract shapes — no weights cross the wire), the service answers
with ranked, memory-fit, workload-aware candidates; timed dry runs
stay client-side where the devices are (the reference's dry-run
workers are device-local too).
"""

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.accelerate.analyser import ModelProfile
from dlrover_tpu.accelerate.strategy import (
    Strategy,
    generate_candidates,
)
from dlrover_tpu.common.comm import MasterChannel, build_master_server
from dlrover_tpu.common.env import get_free_port
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.messages import (
    BoolResponse,
    Message,
    deserialize_message,
)


@dataclass
class StrategyRequest(Message):
    """Client -> service: the analysed model + workload shape."""

    num_params: int = 0
    param_bytes: int = 0
    optimizer_bytes: int = 0
    activation_bytes_per_sample: int = 0
    num_layers: int = 0
    n_devices: int = 1
    batch_per_replica: int = 1
    seq_len: int = 2048
    # the client's REAL global batch (None = unknown): candidates
    # whose batch sharding can't divide it are useless to serve
    global_batch: Optional[int] = None
    long_context: bool = False
    moe: bool = False
    max_candidates: int = 8


@dataclass
class StrategyResponse(Message):
    """Ranked candidates as Strategy kwargs dicts (wire-stable)."""

    candidates: List = field(default_factory=list)
    # true when the ranking used fleet-measured timings (the service's
    # calibrated planner had data for this workload)
    calibrated: bool = False


@dataclass
class StrategyMeasurement(Message):
    """Client -> service: one measured dry-run/production step time.

    The Brain role (reference ``persist_metrics`` RPC +
    ``optimize_job_worker_resource.go``'s learned throughput model):
    clients report what a strategy actually cost; the service
    calibrates its per-term cost model per workload and ranks BETTER
    for the next requester of the same workload."""

    # workload key (same fields the request carries — byte fields
    # included: two jobs with equal param counts but different
    # activation/optimizer footprints are DIFFERENT workloads)
    num_params: int = 0
    param_bytes: int = 0
    optimizer_bytes: int = 0
    activation_bytes_per_sample: int = 0
    num_layers: int = 0
    batch_per_replica: int = 1
    seq_len: int = 2048
    # what was measured
    strategy: Dict = field(default_factory=dict)
    step_time_s: float = 0.0


def _strategy_to_dict(s: Strategy) -> Dict:
    # asdict stays exact as Strategy grows fields (a hand-rolled list
    # would silently drop e.g. pipe_microbatches on the wire)
    import dataclasses

    return dataclasses.asdict(s)


# warn once per distinct unknown-field set: steady-state version skew
# during a rolling upgrade would otherwise log per RPC at fleet scale
_warned_unknown_fields: set = set()


def _strategy_from_dict(kw: Dict) -> Optional[Strategy]:
    """Version-skew-tolerant Strategy reconstruction (both directions
    of a rolling upgrade put unknown fields on the wire).  Unknown
    keys are dropped WITH a (once-per-set) warning — a silently
    defaulted renamed field would corrupt whatever consumes the
    result — and an unconstructible dict returns None."""
    import dataclasses

    known = {f.name for f in dataclasses.fields(Strategy)}
    unknown = tuple(sorted(set(kw) - known))
    if unknown and unknown not in _warned_unknown_fields:
        _warned_unknown_fields.add(unknown)
        logger.warning(
            "strategy wire dict has unknown fields %s (version "
            "skew?); dropping them", list(unknown),
        )
    try:
        return Strategy(**{k: v for k, v in kw.items() if k in known})
    except (TypeError, ValueError) as e:
        logger.warning("unusable strategy dict: %s", e)
        return None


def _workload_key(msg) -> Tuple:
    """Workload identity from a request OR measurement (both carry the
    same profile fields)."""
    return (
        msg.num_params,
        msg.param_bytes,
        msg.optimizer_bytes,
        msg.activation_bytes_per_sample,
        msg.num_layers,
        msg.batch_per_replica,
        msg.seq_len,
    )


class StrategyService:
    """The in-process brain behind the RPC surface (usable directly —
    the service wrapper only adds the wire).

    Reported measurements accumulate per workload; once any exist, the
    ranking for that workload runs through a
    :class:`~dlrover_tpu.accelerate.dim_planner.CalibratedPlanner`
    fitted on them — the fleet teaches the service its real
    compute/comm balance (the reference Brain's datastore + learned
    throughput model)."""

    # newest measurements win; older fleet history ages out (bounds
    # service memory AND keeps the fit tracking current hardware)
    MAX_MEASUREMENTS_PER_WORKLOAD = 64

    def __init__(self, datastore=None, job: str = ""):
        """``datastore``: a
        :class:`~dlrover_tpu.master.datastore.BrainDatastore` making
        the fleet calibration durable across master restarts
        (reference: the Go Brain's MySQL recorders,
        ``dbbase/recorder.go:280``).  None = in-memory only; defaults
        to the process datastore when ``DLROVER_TPU_BRAIN_DB`` is
        set.  ``job`` tags this master's measurements so a SHARED db
        file serves as a multi-job Brain: measurements are keyed by
        workload signature, so job B's planner adopts job A's
        calibration on first touch (defaults to
        ``DLROVER_TPU_JOB_NAME``)."""
        import threading

        # one lock over both maps: the gRPC pool serves record() and
        # generate() concurrently, and a stale planner stored after a
        # concurrent record() would silently drop that measurement
        self._lock = threading.Lock()
        self._measurements: Dict[Tuple, List] = {}
        # fitted planner per workload, invalidated by record()
        self._planners: Dict[Tuple, object] = {}
        if datastore is None:
            from dlrover_tpu.master.datastore import (
                get_default_datastore,
            )

            datastore = get_default_datastore()
        self._datastore = datastore
        self._job = job or os.getenv("DLROVER_TPU_JOB_NAME", "")

    def _load_persisted(self, key: Tuple) -> List:
        """History for ``key`` from the datastore (restart recovery);
        [] when no store, nothing recorded, or the store is broken —
        durability is best-effort, never load-bearing for the RPCs."""
        if self._datastore is None:
            return []
        from dlrover_tpu.master.datastore import workload_signature

        out = []
        try:
            rows = self._datastore.load_measurements(
                workload_signature(key),
                limit=self.MAX_MEASUREMENTS_PER_WORKLOAD,
            )
        except Exception as e:  # noqa: BLE001 - degrade to in-memory
            logger.warning("measurement history load failed: %s", e)
            return []
        for kw, step_time in rows:
            strategy = _strategy_from_dict(kw)
            if strategy is not None:
                out.append((strategy, step_time))
        return out

    def record(self, m: StrategyMeasurement) -> None:
        if m.step_time_s <= 0:
            return
        strategy = _strategy_from_dict(m.strategy)
        if strategy is None:
            return
        key = _workload_key(m)
        with self._lock:
            hist = self._measurements.get(key)
            if hist is None:
                # first touch since (re)start: adopt persisted history
                # so the refit sees the whole fleet record
                hist = self._measurements[key] = self._load_persisted(
                    key
                )
            hist.append((strategy, m.step_time_s))
            del hist[: -self.MAX_MEASUREMENTS_PER_WORKLOAD]
            self._planners.pop(key, None)  # refit on next request
        if self._datastore is not None:
            from dlrover_tpu.master.datastore import (
                workload_signature,
            )

            try:
                self._datastore.record_measurement(
                    workload_signature(key), dict(m.strategy),
                    m.step_time_s, job=self._job,
                )
            except Exception as e:  # noqa: BLE001 - best-effort
                logger.warning("measurement persist failed: %s", e)

    def generate(self, req: StrategyRequest) -> StrategyResponse:
        profile = ModelProfile(
            num_params=req.num_params,
            param_bytes=req.param_bytes,
            largest_leaf=0,
            leaf_count=0,
            optimizer_bytes=req.optimizer_bytes,
            activation_bytes_per_sample=(
                req.activation_bytes_per_sample
            ),
            num_layers=req.num_layers,
        )
        cands = generate_candidates(
            profile,
            req.n_devices,
            long_context=req.long_context,
            moe=req.moe,
            batch_per_replica=req.batch_per_replica,
            seq_len=req.seq_len,
            global_batch=req.global_batch,
        )
        key = _workload_key(req)
        calibrated = False
        with self._lock:
            measured = self._measurements.get(key)
            if measured is None:
                # a restarted master serves calibrated rankings from
                # the durable history before any new reports arrive
                measured = self._measurements[key] = (
                    self._load_persisted(key)
                )
            if measured:
                planner = self._planners.get(key)
                if planner is None:
                    from dlrover_tpu.accelerate.dim_planner import (
                        CalibratedPlanner,
                    )

                    planner = CalibratedPlanner(
                        profile,
                        batch_per_replica=req.batch_per_replica,
                        seq_len=req.seq_len,
                    )
                    planner.calibrate(list(measured))
                    self._planners[key] = planner
                calibrated = True
        if calibrated:
            cands = [s for s, _ in planner.rank(cands)]
        cands = cands[: req.max_candidates]
        return StrategyResponse(
            candidates=[_strategy_to_dict(s) for s in cands],
            calibrated=calibrated,
        )


def start_strategy_service(
    port: int = 0,
) -> Tuple[object, int]:
    """Start the service; returns (grpc server, port)."""
    port = port or get_free_port()
    brain = StrategyService()

    def report_fn(envelope):
        msg = deserialize_message(envelope.data)
        if isinstance(msg, StrategyMeasurement):
            brain.record(msg)
        return BoolResponse(success=True)

    def get_fn(envelope):
        req = deserialize_message(envelope.data)
        if isinstance(req, StrategyRequest):
            return brain.generate(req)
        return None

    server = build_master_server(port, report_fn, get_fn)
    server.start()
    logger.info("strategy service on port %d", port)
    return server, port


class StrategyClient:
    """Client side: profile in, ranked Strategy list out."""

    def __init__(self, addr: str):
        self._channel = MasterChannel(addr)

    def request_candidates(
        self,
        profile: ModelProfile,
        n_devices: int,
        batch_per_replica: int = 1,
        seq_len: int = 2048,
        global_batch: Optional[int] = None,
        long_context: bool = False,
        moe: bool = False,
    ) -> List[Strategy]:
        resp = self._channel.get(
            StrategyRequest(
                num_params=profile.num_params,
                param_bytes=profile.param_bytes,
                optimizer_bytes=profile.optimizer_bytes,
                activation_bytes_per_sample=(
                    profile.activation_bytes_per_sample
                ),
                num_layers=profile.num_layers,
                n_devices=n_devices,
                batch_per_replica=batch_per_replica,
                seq_len=seq_len,
                global_batch=global_batch,
                long_context=long_context,
                moe=moe,
            )
        )
        if resp is None:
            return []
        out = []
        for kw in resp.candidates:
            s = _strategy_from_dict(kw)
            if s is not None:
                out.append(s)
        return out

    def report_measurement(
        self,
        profile: ModelProfile,
        strategy: Strategy,
        step_time_s: float,
        batch_per_replica: int = 1,
        seq_len: int = 2048,
    ) -> bool:
        """Teach the service what this strategy actually cost."""
        return self._channel.report(
            StrategyMeasurement(
                num_params=profile.num_params,
                param_bytes=profile.param_bytes,
                optimizer_bytes=profile.optimizer_bytes,
                activation_bytes_per_sample=(
                    profile.activation_bytes_per_sample
                ),
                num_layers=profile.num_layers,
                batch_per_replica=batch_per_replica,
                seq_len=seq_len,
                strategy=_strategy_to_dict(strategy),
                step_time_s=step_time_s,
            )
        )

    def close(self):
        self._channel.close()
