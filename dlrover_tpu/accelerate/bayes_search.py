"""Bayesian-optimization search over strategy tunables.

Reference parity: the strategy-generation engine's BO algorithm
(``atorch/atorch/auto/engine/sg_algo/bayes_opt_sg.py`` with its
vendored HEBO GP library).  The mesh *factorization* is already
enumerated and ranked analytically (``strategy.generate_candidates``)
and raced by successive halving (``search.successive_halving``); what
is left genuinely black-box are the TUNABLES inside a chosen
factorization — gradient-accumulation micro steps, remat policy,
GPipe microbatch count, flash-attention block sizes — whose cost
surface (compile-time x step-time x memory cliffs) no analytic model
predicts well.  That is the space this module searches.

The surrogate is a small exact Gaussian process (RBF kernel, Cholesky
solve — the space is tens of points, so an exact GP is cheaper and
more predictable than any approximation) with expected improvement as
the acquisition function.  Everything is numpy; no solver or GP
library exists in the image, and none is needed at this scale.
"""

import dataclasses
import itertools
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from dlrover_tpu.common.log import default_logger as logger


class GaussianProcess:
    """Exact GP regression with an RBF kernel on [0,1]^d inputs.

    Hyperparameters are fixed rather than optimized (lengthscale 0.3 of
    the unit cube, noise 1e-4 of signal variance): with <=a few dozen
    observations of a smooth-ish cost surface, marginal-likelihood
    optimization adds failure modes, not accuracy."""

    def __init__(self, lengthscale: float = 0.3, noise: float = 1e-4):
        self.ls = lengthscale
        self.noise = noise
        self._x: Optional[np.ndarray] = None
        self._alpha: Optional[np.ndarray] = None
        self._chol: Optional[np.ndarray] = None
        self._y_mean = 0.0
        self._y_std = 1.0

    def _k(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d2 = np.sum(
            (a[:, None, :] - b[None, :, :]) ** 2, axis=-1
        )
        return np.exp(-0.5 * d2 / (self.ls**2))

    def fit(self, x: np.ndarray, y: np.ndarray) -> None:
        self._x = np.asarray(x, np.float64)
        y = np.asarray(y, np.float64)
        self._y_mean = float(np.mean(y))
        self._y_std = float(np.std(y)) or 1.0
        yn = (y - self._y_mean) / self._y_std
        k = self._k(self._x, self._x)
        k[np.diag_indices_from(k)] += self.noise
        self._chol = np.linalg.cholesky(k)
        self._alpha = np.linalg.solve(
            self._chol.T, np.linalg.solve(self._chol, yn)
        )

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Posterior mean and std (de-standardized) at query points."""
        x = np.asarray(x, np.float64)
        ks = self._k(x, self._x)
        mean = ks @ self._alpha
        v = np.linalg.solve(self._chol, ks.T)
        var = np.clip(1.0 - np.sum(v * v, axis=0), 1e-12, None)
        return (
            mean * self._y_std + self._y_mean,
            np.sqrt(var) * self._y_std,
        )


def _phi_cdf(z: np.ndarray) -> np.ndarray:
    # standard normal cdf via erf (scipy ships as a jax dependency)
    from scipy.special import erf

    return 0.5 * (1.0 + erf(z / np.sqrt(2.0)))


def _phi_pdf(z: np.ndarray) -> np.ndarray:
    return np.exp(-0.5 * z * z) / np.sqrt(2.0 * np.pi)


def expected_improvement(
    mean: np.ndarray, std: np.ndarray, best: float
) -> np.ndarray:
    """EI for MINIMIZATION: E[max(best - f, 0)]."""
    z = (best - mean) / std
    return (best - mean) * _phi_cdf(z) + std * _phi_pdf(z)


class BayesOpt:
    """Sequential minimizer over a discrete config space.

    ``space`` maps knob name -> ordered value list; every knob is
    treated as ordinal and embedded at its normalized index in [0,1]
    (micro steps, block sizes and remat aggressiveness are all
    monotone-ish axes, which is what makes the RBF metric meaningful).

    >>> bo = BayesOpt({"micro": [1, 2, 4], "remat": ["none", "dots",
    ...                "full"]}, seed=0)
    >>> cfg = bo.suggest(); bo.observe(cfg, measured_cost)
    """

    def __init__(
        self,
        space: Dict[str, Sequence],
        seed: int = 0,
        n_init: int = 4,
    ):
        self.space = {k: list(v) for k, v in space.items()}
        self.names = sorted(self.space)
        grid = list(
            itertools.product(*(self.space[n] for n in self.names))
        )
        self._configs: List[Dict] = [
            dict(zip(self.names, combo)) for combo in grid
        ]
        self._embed = np.array(
            [self._encode(c) for c in self._configs], np.float64
        )
        self._rng = np.random.RandomState(seed)
        self._order = self._rng.permutation(len(self._configs))
        self.n_init = min(n_init, len(self._configs))
        self._observed: Dict[int, float] = {}
        self._failed_cost: Optional[float] = None

    def _encode(self, config: Dict) -> List[float]:
        out = []
        for n in self.names:
            vals = self.space[n]
            idx = vals.index(config[n])
            out.append(
                idx / (len(vals) - 1) if len(vals) > 1 else 0.0
            )
        return out

    def _index_of(self, config: Dict) -> int:
        for i, c in enumerate(self._configs):
            if c == config:
                return i
        raise KeyError(f"config not in space: {config}")

    def suggest(self) -> Optional[Dict]:
        """Next config to evaluate; None when the space is exhausted."""
        unobserved = [
            i for i in range(len(self._configs))
            if i not in self._observed
        ]
        if not unobserved:
            return None
        if len(self._observed) < self.n_init:
            for i in self._order:
                if i not in self._observed:
                    return dict(self._configs[i])
        x = self._embed[sorted(self._observed)]
        y = np.array(
            [self._observed[i] for i in sorted(self._observed)]
        )
        gp = GaussianProcess()
        gp.fit(x, y)
        cand = self._embed[unobserved]
        mean, std = gp.predict(cand)
        ei = expected_improvement(mean, std, float(np.min(y)))
        return dict(self._configs[unobserved[int(np.argmax(ei))]])

    def observe(self, config: Dict, cost: Optional[float]) -> None:
        """Record a measurement; ``None``/inf marks a failed build and
        is encoded as worse-than-anything-seen so the GP steers away
        without poisoning the scale."""
        idx = self._index_of(config)
        if cost is None or not np.isfinite(cost):
            seen = [
                v for v in self._observed.values() if np.isfinite(v)
            ]
            cost = (max(seen) if seen else 1.0) * 2.0
        self._observed[idx] = float(cost)

    def best(self) -> Tuple[Optional[Dict], float]:
        if not self._observed:
            return None, float("inf")
        idx = min(self._observed, key=self._observed.get)
        return dict(self._configs[idx]), self._observed[idx]


def tune_strategy(
    build_fn: Callable,
    base,
    space: Dict[str, Sequence],
    budget: int = 8,
    seed: int = 0,
    time_fn: Optional[Callable] = None,
):
    """BO-tune a chosen mesh factorization's tunable knobs.

    ``base`` is a ``Strategy``; each knob in ``space`` must be a field
    of it (e.g. ``num_micro_steps``, ``remat``, ``pipe_microbatches``).
    ``build_fn`` is the same builder the dry runner uses.  Returns
    ``(best_strategy, history)`` where history maps describe->cost.
    """
    from dlrover_tpu.accelerate.dry_runner import time_strategy

    timer = time_fn or time_strategy
    bo = BayesOpt(space, seed=seed)
    history: Dict[str, Optional[float]] = {}
    for _ in range(budget):
        cfg = bo.suggest()
        if cfg is None:
            break
        candidate = dataclasses.replace(base, **cfg)
        cost = timer(build_fn, candidate)
        history[repr(sorted(cfg.items()))] = cost
        bo.observe(cfg, cost)
        logger.info(
            "bayes-tune %s -> %s", cfg, f"{cost:.4f}s" if cost else "fail"
        )
    best_cfg, _ = bo.best()
    if best_cfg is None:
        return base, history
    return dataclasses.replace(base, **best_cfg), history
