"""Timed dry runs of candidate strategies.

Reference parity: ``atorch/atorch/auto/dry_runner/dry_runner.py``
(timed fwd/bwd batches per candidate) driven by the engine's task loop
(``auto/engine/executor.py:36``).  The JAX version compiles the
candidate's sharded train step and times a few real steps — the
compile itself also validates that the sharding is partitionable.
"""

import time
from typing import Callable, List, Optional, Tuple

import jax

from dlrover_tpu.accelerate.strategy import Strategy
from dlrover_tpu.common.log import default_logger as logger


def time_strategy(
    build_fn: Callable[[Strategy], Tuple[Callable, object, object]],
    strategy: Strategy,
    warmup: int = 1,
    steps: int = 3,
) -> Optional[float]:
    """``build_fn(strategy) -> (step_fn, state, batch)``; returns mean
    step seconds or None when the candidate fails to build/compile."""
    try:
        step_fn, state, batch = build_fn(strategy)
        for _ in range(warmup):
            state, metrics = step_fn(state, batch)
        jax.block_until_ready(metrics)
        start = time.perf_counter()
        for _ in range(steps):
            state, metrics = step_fn(state, batch)
        jax.block_until_ready(metrics)
        return (time.perf_counter() - start) / steps
    except Exception as e:  # noqa: BLE001
        logger.warning(
            "strategy %s failed dry run: %s", strategy.describe(), e
        )
        return None


def pick_best(
    build_fn: Callable,
    candidates: List[Strategy],
    max_candidates: int = 4,
) -> Tuple[Optional[Strategy], dict]:
    """Dry-run the top candidates; fastest wins (the reference's
    DRYRUN task phase)."""
    timings = {}
    best, best_t = None, float("inf")
    for s in candidates[:max_candidates]:
        t = time_strategy(build_fn, s)
        timings[s.describe()] = t
        if t is not None and t < best_t:
            best, best_t = s, t
    return best, timings
