"""Strategy search over timed dry runs.

Reference parity: the acceleration engine's strategy-generation
search (``atorch/atorch/auto/engine/sg_algo/combination_sg.py``,
``bayes_opt_sg.py`` + vendored HEBO).  The reference searches a large
mixed space (wrap classes, fp modes, tunable knobs) where a GP
surrogate earns its keep; a TPU strategy space is a handful of mesh
factorizations already ranked by an analytic cost model, so the right
search is **successive halving**: race all finalists for one cheap
step, keep the best half, re-race the survivors with more steps —
compile time dominates, so every candidate pays compilation exactly
once and the extra steps only go to plausible winners.
"""

import math
import time
from typing import Callable, Dict, List, Optional, Tuple

import jax

from dlrover_tpu.accelerate.strategy import Strategy
from dlrover_tpu.common.log import default_logger as logger


class _Runner:
    """One built candidate: compiled step + live (donated) state.

    The train step donates its state buffer, so the state must be
    threaded across rounds — each timing call leaves the runner with
    the latest state instead of rebuilding (and recompiling) the
    candidate."""

    def __init__(self, step_fn, state, batch):
        self.step_fn = step_fn
        self.state = state
        self.batch = batch

    def timed_steps(self, steps: int) -> float:
        state, metrics = self.step_fn(self.state, self.batch)  # warmup
        jax.block_until_ready(metrics)
        start = time.perf_counter()
        for _ in range(steps):
            state, metrics = self.step_fn(state, self.batch)
        jax.block_until_ready(metrics)
        self.state = state
        return (time.perf_counter() - start) / steps


def successive_halving(
    build_fn: Callable,
    candidates: List[Strategy],
    max_candidates: int = 6,
    first_steps: int = 1,
    final_steps: int = 5,
) -> Tuple[Optional[Strategy], Dict[str, List[float]]]:
    """Race the top candidates, halving the field each round while
    doubling the measured steps; every candidate compiles exactly once
    (runners are cached across rounds).  Returns
    (winner, {strategy: [per-round step seconds]})."""
    field = list(candidates[:max_candidates])
    runners: Dict[int, _Runner] = {}
    timings: Dict[str, List[float]] = {}
    steps = first_steps
    rounds = max(1, math.ceil(math.log2(max(len(field), 1))))
    for rnd in range(rounds):
        scored = []
        for s in field:
            try:
                runner = runners.get(id(s))
                if runner is None:
                    runner = _Runner(*build_fn(s))
                    runners[id(s)] = runner
                t = runner.timed_steps(steps)
            except Exception as e:  # noqa: BLE001
                logger.warning(
                    "strategy %s failed dry run: %s", s.describe(), e
                )
                t = None
            timings.setdefault(s.describe(), []).append(
                t if t is not None else float("nan")
            )
            if t is not None:
                scored.append((t, s))
        if not scored:
            return None, timings
        scored.sort(key=lambda ts: ts[0])
        keep = max(1, len(scored) // 2)
        field = [s for _, s in scored[:keep]]
        logger.info(
            "search round %d (%d steps): kept %s",
            rnd, steps, [s.describe() for s in field],
        )
        if len(field) == 1:
            break
        steps = min(final_steps, steps * 2)
    return field[0], timings
