"""Strategy search over timed dry runs.

Reference parity: the acceleration engine's strategy-generation
search (``atorch/atorch/auto/engine/sg_algo/combination_sg.py``,
``bayes_opt_sg.py`` + vendored HEBO).  The reference searches a large
mixed space (wrap classes, fp modes, tunable knobs) where a GP
surrogate earns its keep; a TPU strategy space is a handful of mesh
factorizations already ranked by an analytic cost model, so the right
search is **successive halving**: race all finalists for one cheap
step, keep the best half, re-race the survivors with more steps —
compile time dominates, so every candidate pays compilation exactly
once and the extra steps only go to plausible winners.
"""

import math
from typing import Callable, Dict, List, Optional, Tuple

from dlrover_tpu.accelerate.dry_runner import time_strategy
from dlrover_tpu.accelerate.strategy import Strategy
from dlrover_tpu.common.log import default_logger as logger


def successive_halving(
    build_fn: Callable,
    candidates: List[Strategy],
    max_candidates: int = 6,
    first_steps: int = 1,
    final_steps: int = 5,
) -> Tuple[Optional[Strategy], Dict[str, List[float]]]:
    """Race the top candidates, halving the field each round while
    doubling the measured steps; returns
    (winner, {strategy: [per-round step seconds]}).

    Memory discipline: exactly ONE candidate's train state is live at a
    time — each timing builds, measures, and drops the candidate
    (``time_strategy``).  Candidates were admitted by a memory model
    sized for a single train state at 85% HBM, so caching runners
    across rounds (to save recompiles) would OOM on the second build;
    survivors pay a recompile per round instead, which the halving
    keeps to ~log2(field) extra compiles on the plausible winners
    only."""
    field = list(candidates[:max_candidates])
    timings: Dict[str, List[float]] = {}
    steps = first_steps
    rounds = max(1, math.ceil(math.log2(max(len(field), 1))))
    for rnd in range(rounds):
        scored = []
        for s in field:
            t = time_strategy(build_fn, s, warmup=1, steps=steps)
            timings.setdefault(s.describe(), []).append(
                t if t is not None else float("nan")
            )
            if t is not None:
                scored.append((t, s))
        if not scored:
            return None, timings
        scored.sort(key=lambda ts: ts[0])
        keep = max(1, len(scored) // 2)
        field = [s for _, s in scored[:keep]]
        logger.info(
            "search round %d (%d steps): kept %s",
            rnd, steps, [s.describe() for s in field],
        )
        if len(field) == 1:
            break
        steps = min(final_steps, steps * 2)
    return field[0], timings
