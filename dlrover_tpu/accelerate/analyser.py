"""Model analysis for strategy selection.

Reference parity: ``atorch/atorch/auto/analyser/analyser.py:327``
(model props: #params, submodule census) and ``device_context.py:213``
(GPU memory/flops census).  JAX version works on abstract shapes
(``jax.eval_shape``) so analysis costs nothing and runs without
devices.
"""

from dataclasses import dataclass, field
from typing import Callable, Dict, Tuple

import jax
import numpy as np


@dataclass
class ModelProfile:
    num_params: int
    param_bytes: int  # fp32 master copy
    largest_leaf: int
    leaf_count: int
    # optimizer adds 2 fp32 moments/param for adam-family
    optimizer_bytes: int = 0
    # rough activation bytes per sample at bf16 (caller-supplied)
    activation_bytes_per_sample: int = 0
    # leading dim of the stacked "layers" subtree (0 = no stack):
    # pipeline candidates must divide it evenly into stages
    num_layers: int = 0
    extra: Dict = field(default_factory=dict)

    def train_state_bytes(self) -> int:
        return self.param_bytes + self.optimizer_bytes


def analyse_model(
    init_params_fn: Callable,
    optimizer=None,
    rng_shape=(2,),
) -> ModelProfile:
    """Abstract-shape census of params + optimizer state."""
    shapes = jax.eval_shape(
        init_params_fn,
        jax.ShapeDtypeStruct(rng_shape, np.uint32),
    )
    leaves = jax.tree_util.tree_leaves(shapes)
    num_params = sum(int(np.prod(leaf.shape)) for leaf in leaves)
    param_bytes = sum(
        int(np.prod(leaf.shape)) * leaf.dtype.itemsize for leaf in leaves
    )
    largest = max(
        (int(np.prod(leaf.shape)) for leaf in leaves), default=0
    )
    optimizer_bytes = 0
    if optimizer is not None:
        opt_shapes = jax.eval_shape(optimizer.init, shapes)
        optimizer_bytes = sum(
            int(np.prod(leaf.shape)) * leaf.dtype.itemsize
            for leaf in jax.tree_util.tree_leaves(opt_shapes)
        )
    num_layers = 0
    if isinstance(shapes, dict) and "layers" in shapes:
        layer_leaves = jax.tree_util.tree_leaves(shapes["layers"])
        if layer_leaves and layer_leaves[0].shape:
            num_layers = int(layer_leaves[0].shape[0])
    return ModelProfile(
        num_params=num_params,
        param_bytes=param_bytes,
        largest_leaf=largest,
        leaf_count=len(leaves),
        optimizer_bytes=optimizer_bytes,
        num_layers=num_layers,
    )


def device_memory_bytes(default_gb: float = 16.0) -> int:
    """Per-device HBM (v5e default 16 GB); CPU CI uses the default so
    strategy selection is deterministic."""
    try:
        dev = jax.devices()[0]
        stats = dev.memory_stats()
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"])
    except Exception:  # noqa: BLE001
        pass
    return int(default_gb * (1 << 30))


def fits_in_memory(
    profile: ModelProfile,
    n_devices: int,
    fsdp: int,
    tensor: int,
    batch_per_device: int = 1,
    headroom: float = 0.85,
    pipe: int = 1,
    micro_steps: int = 1,
) -> Tuple[bool, float]:
    """Memory-fit model: params+opt shard over fsdp*tensor*pipe;
    activations scale with the local batch divided by gradient-
    accumulation micro steps.  Returns (fits, utilization)."""
    hbm = device_memory_bytes() * headroom
    shard = max(fsdp * tensor * pipe, 1)
    state = profile.train_state_bytes() / shard
    if micro_steps > 1:
        # the accumulation train path carries a full fp32 param-shaped
        # grad_sum through its scan, on top of the per-micro-step
        # gradients — unmodeled it OOMs exactly the candidates that
        # accumulation was supposed to rescue
        state += profile.num_params * 4.0 / shard
    acts = (
        profile.activation_bytes_per_sample
        * batch_per_device
        / max(micro_steps, 1)
    )
    used = state + acts
    return used <= hbm, used / hbm
