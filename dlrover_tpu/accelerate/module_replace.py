"""Strategy-driven kernel selection — the module-replace analog.

Reference parity: ``atorch/atorch/auto/opt_lib/
module_replace_optimization.py:179`` (swaps a model's attention modules
for flash-attention implementations as an optimization pass).  On TPU
there are no modules to rewrite: the model's ``forward`` takes a
pluggable ``attention_fn``, and this pass picks the kernel that matches
the active strategy:

- sequence axis > 1  -> ring attention (``lax.ppermute`` KV rotation)
  under ``shard_map``, seq-sharded end to end;
- TPU backend        -> the Pallas flash-attention kernel;
- otherwise          -> the dense reference kernel (XLA fuses it well
  enough on CPU CI, and Pallas interpret mode would be slower).

``dlrover_tpu.models.llama.forward`` resolves its default attention
through :func:`select_attention` at trace time, so a train step built
by ``auto_accelerate`` automatically runs the right kernel with no user
plumbing (the same invisibility the reference achieves with module
surgery).
"""

import os
from functools import partial
from typing import Optional

import jax

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.parallel.mesh import AxisName, MeshContext
from dlrover_tpu.parallel.sharding import (
    BATCH,
    HEADS,
    KV_HEADS,
    SEQ,
    LogicalAxisRules,
    filter_spec_for_mesh,
)

# test/override hook: "auto" | "1" (force flash) | "0" (force dense)
FLASH_ENV = "DLROVER_TPU_FLASH_ATTENTION"
# test/override hook: "auto" | "ring" | "ulysses"
SP_KERNEL_ENV = "DLROVER_TPU_SP_KERNEL"
# solver-chosen flash tiles, "block_q,block_kv" (empty = measured
# defaults); accelerate.solve_joint_plan emits the pair
FLASH_BLOCKS_ENV = "DLROVER_TPU_FLASH_BLOCKS"


def _tile_multiple(dtype) -> int:
    """Smallest legal sublane tile for the flash kernel's seq-blocked
    dimension on TPU (Mosaic min tiles: fp32 (8,128), bf16/fp16
    (16,128), 1-byte types (32,128))."""
    import numpy as np

    dt = np.dtype(dtype) if not hasattr(dtype, "itemsize") else dtype
    if dt.itemsize >= 4:
        return 8
    if dt.itemsize == 2:
        return 16
    return 32


def round_block_to_tile(block: int, local_seq: int, dtype) -> int:
    """Clamp a solver/env flash-block override to the LOCAL sequence,
    rounding DOWN to the largest supported tile multiple that fits.

    A bare ``min(block, local_seq)`` can hand the Pallas kernel a
    non-tile-aligned block (e.g. override 256 against a local seq of
    100 → 100, not a multiple of the (8|16|32, 128) Mosaic tile) and
    fail at kernel build.  When the local sequence is itself below one
    tile, the kernel's internal ``min(block, s)`` + bounds masks
    handle the padding — return the local seq unchanged."""
    tile = _tile_multiple(dtype)
    b = min(int(block), int(local_seq))
    if local_seq < tile:
        return b
    return max(b - b % tile, tile)


def _flash_enabled(flash: Optional[bool]) -> bool:
    if flash is not None:
        return flash
    env = os.getenv(FLASH_ENV, "auto").lower()
    if env in ("1", "true", "on"):
        return True
    if env in ("0", "false", "off"):
        return False
    return jax.default_backend() == "tpu"


def sp_kernel_choice(
    seq_size: int, n_heads: int, n_kv_heads: int
) -> str:
    """Which sequence-parallel attention form to run: "ulysses" when
    both head counts divide the seq axis (one all-to-all exchanging
    seq<->head beats n ring hops on ICI — reference ships both as
    selectable optimizations, ``sequence_parallel_optimization.py:9``),
    "ring" otherwise (works for any head count, overlaps compute with
    the ppermute rotation)."""
    env = os.getenv(SP_KERNEL_ENV, "auto").lower()
    if env in ("ring", "ulysses"):
        return env
    if n_heads % seq_size == 0 and n_kv_heads % seq_size == 0:
        return "ulysses"
    return "ring"


def select_attention(
    mesh_ctx: Optional[MeshContext],
    rules: Optional[LogicalAxisRules],
    flash: Optional[bool] = None,
):
    """Return the attention kernel for the current strategy.

    The returned callable has the model kernel signature
    ``(q[B,S,H,D], k[B,S,KV,D], v, causal=True) -> [B,S,H,D]``.
    """
    import importlib

    # the package re-exports the function under the same name as the
    # module, so attribute-style imports resolve to the function
    _fa = importlib.import_module("dlrover_tpu.ops.flash_attention")
    _llama = importlib.import_module("dlrover_tpu.models.llama")

    use_flash = _flash_enabled(flash)
    inner = (
        _fa.flash_attention if use_flash
        else _llama.dot_product_attention
    )
    if use_flash:
        # tile override: apply a solver-chosen flash tile without
        # touching model code
        blocks = os.getenv(FLASH_BLOCKS_ENV, "")
        if blocks:
            try:
                bq, bk = (int(x) for x in blocks.split(","))
                if bq <= 0 or bk <= 0:
                    raise ValueError("blocks must be positive")
                # clamp to the LOCAL sequence at call time: under a
                # seq-sharded mesh the kernel sees seq/s.seq, and a
                # well-formed override sized for the global seq would
                # otherwise fail at kernel build (ADVICE-r4).  The
                # clamp point is the first place local shapes exist;
                # the clamped block additionally rounds DOWN to the
                # largest supported Mosaic tile multiple — a bare min
                # (override 256, local seq 100 → 100) is not a legal
                # tile and dies at kernel build.
                base = inner

                def inner(q, k, v, *a, _base=base, _bq=bq, _bk=bk,
                          **kw):
                    lbq = round_block_to_tile(
                        _bq, q.shape[1], q.dtype
                    )
                    lbk = round_block_to_tile(
                        _bk, k.shape[1], k.dtype
                    )
                    if (lbq, lbk) != (_bq, _bk):
                        reason = (
                            "exceeds local seq"
                            if _bq > q.shape[1] or _bk > k.shape[1]
                            else "is not a Mosaic tile multiple"
                        )
                        logger.warning(
                            "%s=%r %s (local q=%d k=%d); adjusted "
                            "to tile-aligned %d,%d",
                            FLASH_BLOCKS_ENV, blocks, reason,
                            q.shape[1], k.shape[1], lbq, lbk,
                        )
                    return _base(
                        q, k, v, *a, block_q=lbq, block_k=lbk, **kw
                    )
            except ValueError:
                logger.warning(
                    "ignoring malformed %s=%r",
                    FLASH_BLOCKS_ENV, blocks,
                )

    seq_size = (
        mesh_ctx.axis_size(AxisName.SEQUENCE) if mesh_ctx else 1
    )
    if seq_size <= 1 or rules is None:
        return inner
    return _sp_under_shard_map(mesh_ctx, rules, inner, use_flash)


def select_layer_executor(mesh_ctx: Optional[MeshContext]):
    """How the model's stacked layer dim is executed: a plain
    ``lax.scan`` normally; the GPipe shard_map pipeline when the
    strategy runs pipe > 1 (reference
    ``pipeline_parallel_optimization.py:56`` — PiPPy graph-split; the
    TPU-native form is SPMD microbatch ppermute,
    ``dlrover_tpu.parallel.pipeline``).

    Executor signature: ``(block, layers, x, *extras) -> x`` where
    ``block(layer_params, x, *extras) -> x`` is one layer and
    ``layers`` is the stacked param pytree (leading dim = layer)."""
    pipe_size = (
        mesh_ctx.axis_size(AxisName.PIPELINE) if mesh_ctx else 1
    )
    if pipe_size <= 1:
        return _scan_layers
    return _pipeline_executor(mesh_ctx)


def _scan_layers(block, layers, x, *extras):
    import jax

    def body(h, lp):
        return block(lp, h, *extras), None

    h, _ = jax.lax.scan(body, x, layers)
    return h


def _pipeline_executor(mesh_ctx: MeshContext):
    """GPipe over the "pipe" mesh axis: layers sharded into stages,
    activations microbatched and rotated stage-to-stage with ppermute.
    Partial-manual shard_map — only "pipe" is manual, every other mesh
    axis stays auto so GSPMD keeps inserting the dp/fsdp/tp collectives
    inside the stage body."""
    import jax
    from jax.sharding import PartitionSpec as P

    from dlrover_tpu.parallel.pipeline import (
        merge_microbatches,
        pipeline_spmd,
        split_microbatches,
    )

    mesh = mesh_ctx.mesh
    n_stages = mesh_ctx.axis_size(AxisName.PIPELINE)
    num_mb = mesh_ctx.pipeline_microbatches or 2 * n_stages
    logger.info(
        "module_replace: GPipe executor, %d stages x %d microbatches",
        n_stages, num_mb,
    )

    def execute(block, layers, x, *extras):
        import jax.numpy as jnp

        # f32 at the shard_map boundary: the VJP psums the replicated
        # input's cotangent over the manual pipe axis, and a bf16 psum
        # under partial-manual shard_map crashes XLA CPU (same
        # float-normalization bug as pipeline_spmd's broadcast)
        x_dtype = x.dtype
        upcast = x_dtype in (jnp.bfloat16, jnp.float16)

        def run(layers_local, x_local, *extras_local):
            x_local = x_local.astype(x_dtype)

            def stage_fn(stage_layers, x_mb):
                def body(h, lp):
                    return block(lp, h, *extras_local), None

                h, _ = jax.lax.scan(body, x_mb, stage_layers)
                return h

            mbs = split_microbatches(x_local, num_mb)
            out = pipeline_spmd(
                stage_fn, layers_local, mbs,
                axis_name=AxisName.PIPELINE,
            )
            return merge_microbatches(out)

        layer_specs = jax.tree_util.tree_map(
            lambda _: P(AxisName.PIPELINE), layers
        )
        rep = P()
        extras_specs = tuple(rep for _ in extras)
        x_in = x.astype(jnp.float32) if upcast else x
        extras_in = tuple(
            e.astype(jnp.float32)
            if e.dtype in (jnp.bfloat16, jnp.float16)
            else e
            for e in extras
        )
        from dlrover_tpu.parallel.sharding import shard_map_compat

        return shard_map_compat(
            run,
            mesh=mesh,
            in_specs=(layer_specs, rep) + extras_specs,
            out_specs=rep,
            manual_axes={AxisName.PIPELINE},
        )(layers, x_in, *extras_in)

    return execute


def _sp_under_shard_map(mesh_ctx: MeshContext,
                        rules: LogicalAxisRules,
                        inner_attention,
                        use_flash: bool = True):
    """Sequence-parallel attention over the seq mesh axis, wrapped in
    shard_map with specs matching the activation rule table (so it
    composes with the surrounding GSPMD program).

    The SP form is picked per call site from the traced head counts
    (:func:`sp_kernel_choice`): Ulysses all-to-all when heads divide
    the axis, ring otherwise.  Ulysses runs ``inner_attention`` (the
    Pallas flash kernel on TPU) on the gathered sequence; the ring's
    per-block kernel is flash via ``flash_attention_lse``."""
    from dlrover_tpu.parallel.collectives import (
        ring_attention,
        ulysses_attention,
    )

    mesh = mesh_ctx.mesh
    seq_size = mesh_ctx.axis_size(AxisName.SEQUENCE)
    q_spec = filter_spec_for_mesh(
        rules.spec((BATCH, SEQ, HEADS, None)), mesh
    )
    kv_spec = filter_spec_for_mesh(
        rules.spec((BATCH, SEQ, KV_HEADS, None)), mesh
    )

    # inside the manual region the heads dim is already tensor-sharded
    # (HEADS/KV_HEADS -> tensor axis): Ulysses' all_to_all must divide
    # the LOCAL head count, not the global one
    tp = mesh_ctx.axis_size(AxisName.TENSOR)

    def _tp_split(logical) -> int:
        target = rules.mesh_axes(logical)
        flat = target if isinstance(target, tuple) else (target,)
        return tp if AxisName.TENSOR in flat else 1

    h_split = _tp_split(HEADS)
    kv_split = _tp_split(KV_HEADS)

    def attention(q, k, v, causal: bool = True):
        choice = sp_kernel_choice(
            seq_size, q.shape[2] // h_split, k.shape[2] // kv_split
        )
        logger.info(
            "module_replace: %s attention over %d-way seq axis "
            "(q spec %s)", choice, seq_size, q_spec,
        )
        if choice == "ulysses":
            fn = partial(
                ulysses_attention,
                axis_name=AxisName.SEQUENCE,
                inner_attention=inner_attention,
                causal=causal,
            )
        else:
            # a tile override carried by the inner partial must reach
            # the ring's per-block kernel too — seq-sharded strategies
            # are exactly where the solver sizes tiles for the LOCAL
            # sequence
            tile_kwargs = getattr(inner_attention, "keywords", {})
            fn = partial(
                ring_attention,
                axis_name=AxisName.SEQUENCE,
                causal=causal,
                use_flash=use_flash,
                block_q=tile_kwargs.get("block_q"),
                block_k=tile_kwargs.get("block_k"),
            )
        # inside another manual region (the pipe executor's
        # partial-manual shard_map), the inner map must be built on
        # the AMBIENT abstract mesh — passing the concrete mesh trips
        # "context mesh should match" because pipe is already Manual
        import jax as _jax

        from dlrover_tpu.parallel.sharding import shard_map_compat

        use_mesh = mesh
        try:
            cur = _jax.sharding.get_abstract_mesh()
        except AttributeError:  # older jax: no abstract-mesh API
            cur = None
        if cur is not None and getattr(cur, "axis_names", ()):
            if any(
                "Manual" in str(t)
                for t in getattr(cur, "axis_types", ())
            ):
                use_mesh = cur
        sp = shard_map_compat(
            fn,
            mesh=use_mesh,
            in_specs=(q_spec, kv_spec, kv_spec),
            out_specs=q_spec,
        )
        return sp(q, k, v)

    return attention
