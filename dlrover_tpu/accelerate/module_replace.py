"""Strategy-driven kernel selection — the module-replace analog.

Reference parity: ``atorch/atorch/auto/opt_lib/
module_replace_optimization.py:179`` (swaps a model's attention modules
for flash-attention implementations as an optimization pass).  On TPU
there are no modules to rewrite: the model's ``forward`` takes a
pluggable ``attention_fn``, and this pass picks the kernel that matches
the active strategy:

- sequence axis > 1  -> ring attention (``lax.ppermute`` KV rotation)
  under ``shard_map``, seq-sharded end to end;
- TPU backend        -> the Pallas flash-attention kernel;
- otherwise          -> the dense reference kernel (XLA fuses it well
  enough on CPU CI, and Pallas interpret mode would be slower).

``dlrover_tpu.models.llama.forward`` resolves its default attention
through :func:`select_attention` at trace time, so a train step built
by ``auto_accelerate`` automatically runs the right kernel with no user
plumbing (the same invisibility the reference achieves with module
surgery).
"""

import os
from functools import partial
from typing import Optional

import jax

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.parallel.mesh import AxisName, MeshContext
from dlrover_tpu.parallel.sharding import (
    BATCH,
    HEADS,
    KV_HEADS,
    SEQ,
    LogicalAxisRules,
    filter_spec_for_mesh,
)

# test/override hook: "auto" | "1" (force flash) | "0" (force dense)
FLASH_ENV = "DLROVER_TPU_FLASH_ATTENTION"


def _flash_enabled(flash: Optional[bool]) -> bool:
    if flash is not None:
        return flash
    env = os.getenv(FLASH_ENV, "auto").lower()
    if env in ("1", "true", "on"):
        return True
    if env in ("0", "false", "off"):
        return False
    return jax.default_backend() == "tpu"


def select_attention(
    mesh_ctx: Optional[MeshContext],
    rules: Optional[LogicalAxisRules],
    flash: Optional[bool] = None,
):
    """Return the attention kernel for the current strategy.

    The returned callable has the model kernel signature
    ``(q[B,S,H,D], k[B,S,KV,D], v, causal=True) -> [B,S,H,D]``.
    """
    import importlib

    # the package re-exports the function under the same name as the
    # module, so attribute-style imports resolve to the function
    _fa = importlib.import_module("dlrover_tpu.ops.flash_attention")
    _llama = importlib.import_module("dlrover_tpu.models.llama")

    use_flash = _flash_enabled(flash)
    inner = (
        _fa.flash_attention if use_flash
        else _llama.dot_product_attention
    )

    seq_size = (
        mesh_ctx.axis_size(AxisName.SEQUENCE) if mesh_ctx else 1
    )
    if seq_size <= 1 or rules is None:
        return inner
    return _ring_under_shard_map(mesh_ctx, rules)


def _ring_under_shard_map(mesh_ctx: MeshContext,
                          rules: LogicalAxisRules):
    """Ring attention over the sequence mesh axis, wrapped in shard_map
    with specs matching the activation rule table (so it composes with
    the surrounding GSPMD program)."""
    from jax import shard_map

    from dlrover_tpu.parallel.collectives import ring_attention

    mesh = mesh_ctx.mesh
    q_spec = filter_spec_for_mesh(
        rules.spec((BATCH, SEQ, HEADS, None)), mesh
    )
    kv_spec = filter_spec_for_mesh(
        rules.spec((BATCH, SEQ, KV_HEADS, None)), mesh
    )
    logger.info(
        "module_replace: ring attention over %d-way seq axis "
        "(q spec %s)", mesh_ctx.axis_size(AxisName.SEQUENCE), q_spec,
    )

    def attention(q, k, v, causal: bool = True):
        ring = shard_map(
            partial(
                ring_attention,
                axis_name=AxisName.SEQUENCE,
                causal=causal,
            ),
            mesh=mesh,
            in_specs=(q_spec, kv_spec, kv_spec),
            out_specs=q_spec,
            check_vma=False,
        )
        return ring(q, k, v)

    return attention
