from dlrover_tpu.accelerate.api import (  # noqa: F401
    AccelerateResult,
    auto_accelerate,
)
from dlrover_tpu.accelerate.strategy import Strategy, load_strategy  # noqa: F401
