from dlrover_tpu.accelerate.api import (  # noqa: F401
    AccelerateResult,
    auto_accelerate,
)
from dlrover_tpu.accelerate.strategy import Strategy, load_strategy  # noqa: F401
from dlrover_tpu.accelerate.engine_service import (  # noqa: F401
    StrategyClient,
    start_strategy_service,
)
from dlrover_tpu.accelerate.search import successive_halving  # noqa: F401
from dlrover_tpu.accelerate.bayes_search import (  # noqa: F401
    BayesOpt,
    tune_strategy,
)
from dlrover_tpu.accelerate.dim_planner import (  # noqa: F401
    CalibratedPlanner,
)
from dlrover_tpu.accelerate.solver import (  # noqa: F401
    JointPlan,
    solve as solve_joint_plan,
)
