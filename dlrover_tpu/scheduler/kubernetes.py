"""k8s client wrapper + job args (scheduler abstraction).

Reference parity: ``dlrover/python/scheduler/kubernetes.py`` (the
``k8sClient`` singleton every watcher/scaler uses) and
``scheduler/job.py:70`` (``JobArgs``).  The ``kubernetes`` package is
optional (not in the TPU image); all methods raise a clear error
without it, and tests inject fakes — the reference's own test strategy
(``mock.patch`` of k8sClient, SURVEY.md §4).
"""

import threading
from dataclasses import dataclass, field
from typing import Dict, Optional

from dlrover_tpu.common.constants import DistributionStrategy
from dlrover_tpu.common.log import default_logger as logger

try:  # pragma: no cover - not installed in the TPU image
    from kubernetes import client as k8s_api
    from kubernetes import config as k8s_config
    from kubernetes import watch as k8s_watch
except ImportError:
    k8s_api = None
    k8s_config = None
    k8s_watch = None


@dataclass
class NodeGroupArgs:
    count: int = 0
    resource: str = ""  # "cpu=4,memory=8192,tpu_chips=4"
    restart_count: int = 3
    critical: bool = False


@dataclass
class JobArgs:
    """Per-job config assembled from the platform (CRD/env)."""

    platform: str = "local"
    namespace: str = "default"
    job_name: str = "job"
    distribution_strategy: str = DistributionStrategy.ALLREDUCE
    node_groups: Dict[str, NodeGroupArgs] = field(default_factory=dict)
    relaunch_on_worker_failure: int = 3
    remove_exited_node: bool = True
    tpu_type: str = ""
    tpu_topology: str = ""


class k8sClient:
    """Thin wrapper over the k8s CoreV1/CustomObjects APIs."""

    _instance: Optional["k8sClient"] = None
    _lock = threading.Lock()

    def __init__(self, namespace: str = "default"):
        if k8s_api is None:
            raise RuntimeError(
                "the kubernetes package is not installed; inject a "
                "fake client or run platform=local"
            )
        try:
            k8s_config.load_incluster_config()
        except Exception:  # noqa: BLE001
            k8s_config.load_kube_config()
        self.namespace = namespace
        self.core = k8s_api.CoreV1Api()
        self.custom = k8s_api.CustomObjectsApi()

    @classmethod
    def singleton_instance(cls, namespace: str = "default") -> "k8sClient":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls(namespace)
            return cls._instance

    # ------------------------------------------------------------- pods
    def create_pod(self, manifest: Dict):
        return self.core.create_namespaced_pod(self.namespace, manifest)

    def delete_pod(self, name: str):
        return self.core.delete_namespaced_pod(name, self.namespace)

    def list_pods(self, label_selector: str = ""):
        return self.core.list_namespaced_pod(
            self.namespace, label_selector=label_selector
        )

    def count_pods(self, job_name: str, node_type: str) -> int:
        pods = self.list_pods(
            f"job={job_name},node-type={node_type}"
        )
        return len(pods.items)

    def watch_pods(self, label_selector: str = ""):
        w = k8s_watch.Watch()
        return w.stream(
            self.core.list_namespaced_pod,
            self.namespace,
            label_selector=label_selector,
        )

    # ------------------------------------------------------ custom CRDs
    def create_custom_resource(self, group: str, version: str,
                               plural: str, body: Dict):
        return self.custom.create_namespaced_custom_object(
            group, version, self.namespace, plural, body
        )

    def get_custom_resource(self, group: str, version: str,
                            plural: str, name: str):
        return self.custom.get_namespaced_custom_object(
            group, version, self.namespace, plural, name
        )

    def list_custom_resource(self, group: str, version: str,
                             plural: str):
        return self.custom.list_namespaced_custom_object(
            group, version, self.namespace, plural
        )

    def update_custom_resource_status(self, group: str, version: str,
                                      plural: str, name: str,
                                      body: Dict):
        return self.custom.patch_namespaced_custom_object_status(
            group, version, self.namespace, plural, name, body
        )


def new_job_args(platform: str = "local", job_name: str = "job",
                 **kwargs) -> JobArgs:
    """Factory (reference ``scheduler/factory.py:33``)."""
    args = JobArgs(platform=platform, job_name=job_name, **kwargs)
    if platform == "local" and not args.node_groups:
        args.node_groups = {"worker": NodeGroupArgs(count=1)}
    logger.info("job args: %s", args)
    return args
