"""Ray platform variant: actor-based scheduler/watcher/scaler.

Reference parity: ``dlrover/python/scheduler/ray.py`` (Ray job args +
actor client), ``master/watcher/ray_watcher.py:109`` (actor watcher)
and ``master/scaler/ray_scaler.py:39`` (``ActorScaler``).  The master
treats Ray exactly like k8s: nodes are named units some cluster
substrate runs, a watcher turns substrate state into ``NodeEvent``s
and a scaler executes ``ScalePlan``s — only this module knows the
substrate is Ray actors instead of pods.

The ``ray`` package is not in the TPU image; like the k8s client, the
real client import-gates and everything is injectable — the module
ships :class:`FakeRayClient` (an in-memory actor table) that tests and
local dry runs use, mirroring ``FakeWatcher``.
"""

import threading
import time
from typing import Callable, Dict, List, Optional

from dlrover_tpu.common.constants import (
    NodeEventType,
    NodeStatus,
    NodeType,
)
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.messages import ScalePlan
from dlrover_tpu.common.node import Node, NodeResource
from dlrover_tpu.master.job_manager import NodeEvent
from dlrover_tpu.master.scaler import Scaler
from dlrover_tpu.master.watcher import NodeWatcher

try:  # pragma: no cover - ray is not installed in the TPU image
    import ray
except ImportError:
    ray = None

# Ray actor states -> node lifecycle states
_ACTOR_STATE_TO_STATUS = {
    "DEPENDENCIES_UNREADY": NodeStatus.PENDING,
    "PENDING_CREATION": NodeStatus.PENDING,
    "ALIVE": NodeStatus.RUNNING,
    "RESTARTING": NodeStatus.PENDING,
    "DEAD": NodeStatus.FAILED,
}


def actor_state_to_status(state: str, exit_ok: bool = False) -> str:
    if state == "DEAD" and exit_ok:
        return NodeStatus.SUCCEEDED
    return _ACTOR_STATE_TO_STATUS.get(state, NodeStatus.UNKNOWN)


class RayClient:
    """Thin wrapper over the Ray actor APIs (list/create/kill); every
    consumer takes a client instance so tests inject fakes."""

    _instance = None
    _lock = threading.Lock()

    def __init__(self, namespace: str = "dlrover_tpu"):
        if ray is None:
            raise RuntimeError(
                "the ray package is not installed; inject a "
                "FakeRayClient or run on the k8s/local platform"
            )
        self._namespace = namespace
        if not ray.is_initialized():  # pragma: no cover
            ray.init(namespace=namespace, ignore_reinit_error=True)

    @classmethod
    def singleton_instance(cls, namespace: str = "dlrover_tpu"):
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls(namespace)
        return cls._instance

    def create_actor(
        self, name: str, actor_cls, resource: NodeResource, **kwargs
    ):  # pragma: no cover - requires a ray cluster
        options = {
            "name": name,
            "lifetime": "detached",
            "num_cpus": resource.cpu or 1,
        }
        if resource.tpu_chips:
            options["resources"] = {"TPU": resource.tpu_chips}
        return actor_cls.options(**options).remote(**kwargs)

    def remove_actor(self, name: str):  # pragma: no cover
        try:
            ray.kill(ray.get_actor(name, namespace=self._namespace))
        except ValueError:
            pass

    def list_actors(self) -> List[Dict]:  # pragma: no cover
        from ray.util.state import list_actors

        out = []
        for a in list_actors(detail=True):
            if not a.name:
                continue
            # a DEAD actor's death cause distinguishes clean exits and
            # intentional kills (INTENDED_*) from crashes
            cause = str(getattr(a, "death_cause", "") or "")
            out.append(
                {
                    "name": a.name,
                    "state": a.state,
                    "exit_ok": "INTENDED" in cause.upper(),
                }
            )
        return out


class FakeRayClient:
    """In-memory actor table with the same surface the watcher/scaler
    consume; tests drive it by mutating ``actors`` / calling
    ``set_state``."""

    def __init__(self):
        self.actors: Dict[str, Dict] = {}
        self.created: List[str] = []
        self.removed: List[str] = []

    def create_actor(self, name: str, actor_cls=None,
                     resource: Optional[NodeResource] = None, **kwargs):
        # reusing a DEAD actor's name overwrites the stale entry,
        # matching Ray's named detached actor semantics
        self.actors[name] = {
            "name": name, "state": "PENDING_CREATION", "exit_ok": False,
        }
        self.created.append(name)

    def remove_actor(self, name: str):
        # real Ray keeps killed actors in the table as DEAD with an
        # INTENDED death cause until GC; model that, not deletion
        if name in self.actors:
            self.actors[name]["state"] = "DEAD"
            self.actors[name]["exit_ok"] = True
        self.removed.append(name)

    def gc_actor(self, name: str):
        """Simulate the actor-table GC finally dropping an entry."""
        self.actors.pop(name, None)

    def set_state(self, name: str, state: str, exit_ok: bool = False):
        if name in self.actors:
            self.actors[name]["state"] = state
            self.actors[name]["exit_ok"] = exit_ok

    def list_actors(self) -> List[Dict]:
        return list(self.actors.values())


def _actor_name(job_name: str, node_type: str, node_id: int) -> str:
    return f"{job_name}-{node_type}-{node_id}"


def _parse_actor_name(name: str):
    """job-type-id -> (node_type, node_id) or None for foreign actors."""
    parts = name.rsplit("-", 2)
    if len(parts) != 3:
        return None
    _, node_type, id_str = parts
    try:
        return node_type, int(id_str)
    except ValueError:
        return None


class ActorWatcher(NodeWatcher):
    """Poll the Ray actor table; emit a NodeEvent per state change
    (Ray has no k8s-style watch stream for actors — the reference's
    ray watcher polls too)."""

    def __init__(
        self,
        job_name: str,
        client,
        poll_interval: float = 2.0,
    ):
        self._job_name = job_name
        self._client = client
        self._interval = poll_interval
        self._stopped = threading.Event()
        self._last: Dict[str, str] = {}

    def _actor_to_node(self, info: Dict) -> Optional[Node]:
        name = info.get("name", "")
        if not name.startswith(self._job_name + "-"):
            return None
        parsed = _parse_actor_name(name)
        if parsed is None:
            return None
        node_type, node_id = parsed
        return Node(
            node_type=node_type,
            node_id=node_id,
            name=name,
            status=actor_state_to_status(
                info.get("state", ""),
                exit_ok=bool(info.get("exit_ok", False)),
            ),
        )

    def list(self) -> List[Node]:
        nodes = []
        for info in self._client.list_actors():
            node = self._actor_to_node(info)
            if node is not None:
                nodes.append(node)
        return nodes

    def watch(self, handler: Callable[[NodeEvent], None]):
        while not self._stopped.is_set():
            try:
                seen: Dict[str, str] = {}
                for info in self._client.list_actors():
                    node = self._actor_to_node(info)
                    if node is None:
                        continue
                    seen[node.name] = node.status
                    if self._last.get(node.name) != node.status:
                        handler(
                            NodeEvent(NodeEventType.MODIFIED, node)
                        )
                # an actor vanishing from the table is a deletion
                for name in set(self._last) - set(seen):
                    parsed = _parse_actor_name(name)
                    if parsed is None:
                        continue
                    node_type, node_id = parsed
                    handler(
                        NodeEvent(
                            NodeEventType.DELETED,
                            Node(
                                node_type=node_type,
                                node_id=node_id,
                                name=name,
                                status=NodeStatus.DELETED,
                            ),
                        )
                    )
                self._last = seen
            except Exception as e:  # noqa: BLE001
                logger.warning("actor watch error: %s", e)
            self._stopped.wait(self._interval)

    def stop(self):
        self._stopped.set()


class ActorScaler(Scaler):
    """Execute ScalePlans against the Ray actor table (reference
    ``ray_scaler.py:39``): group resources set target counts, explicit
    launch/remove lists override."""

    def __init__(self, job_name: str, client, actor_cls=None):
        super().__init__(job_name)
        self._client = client
        self._actor_cls = actor_cls

    def _existing(self, node_type: str) -> Dict[int, str]:
        """LIVE actors only: a DEAD entry lingers in Ray's actor table
        but holds no slot — counting it would leave a crashed worker
        permanently unreplaced."""
        out = {}
        for info in self._client.list_actors():
            name = info.get("name", "")
            if not name.startswith(self._job_name + "-"):
                continue
            if info.get("state") == "DEAD":
                continue
            parsed = _parse_actor_name(name)
            if parsed and parsed[0] == node_type:
                out[parsed[1]] = name
        return out

    @staticmethod
    def _group_resource(group: Dict) -> NodeResource:
        resource = group.get("resource", "")
        if isinstance(resource, str):
            return NodeResource.resource_str_to_node_resource(resource)
        return resource

    def scale(self, plan: ScalePlan):
        """Plan convention (shared with TpuPodScaler):
        ``node_group_resources`` = {type: {"count": N, ...}},
        ``remove_nodes`` = actor names, ``launch_nodes`` /
        ``migrate_nodes`` values = node-spec dicts."""
        for node_type, group in plan.node_group_resources.items():
            count = group.get("count", 0)
            resource = self._group_resource(group)
            existing = self._existing(node_type)
            # scale up: fill the smallest free ids (a DEAD actor's
            # name is reusable — Ray frees it on death)
            next_id = 0
            while len(existing) < count:
                while next_id in existing:
                    next_id += 1
                name = _actor_name(self._job_name, node_type, next_id)
                self._client.create_actor(
                    name, self._actor_cls, resource
                )
                existing[next_id] = name
                logger.info("ray scale-up: %s", name)
            # scale down: drop the highest ids first
            for node_id in sorted(existing, reverse=True):
                if len(existing) <= count:
                    break
                self._client.remove_actor(existing.pop(node_id))
        for name in plan.remove_nodes:
            self._client.remove_actor(name)
        for node_spec in plan.launch_nodes:
            node_type = node_spec.get("type", NodeType.WORKER)
            existing = self._existing(node_type)
            next_id = 0
            while next_id in existing:
                next_id += 1
            self._client.create_actor(
                _actor_name(self._job_name, node_type, next_id),
                self._actor_cls,
                self._group_resource(node_spec),
            )
        # migrate = launch a replacement, then kill the old actor
        for name, node_spec in plan.migrate_nodes.items():
            node_type = node_spec.get("type", NodeType.WORKER)
            existing = self._existing(node_type)
            next_id = 0
            while next_id in existing:
                next_id += 1
            self._client.create_actor(
                _actor_name(self._job_name, node_type, next_id),
                self._actor_cls,
                self._group_resource(node_spec),
            )
            self._client.remove_actor(name)
