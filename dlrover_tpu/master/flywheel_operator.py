"""Brain-arbitrated train/serve device lending (ISSUE 20).

The RLHF flywheel runs two resource planes off one chip pool: the
learner (data-parallel trainer ranks) and the rollout fleet (serving
replicas).  Whichever plane is the bottleneck, the other is idle
capital — so the Brain arbitrates:

- **lend** (train -> serve): the rollout plane is the bottleneck
  (sustained dispatch-queue depth per replica above
  ``DLROVER_TPU_FLYWHEEL_LEND_Q``).  One trainer rank drains (the
  PR-9 preemption-drain discipline — a mid-step drain loses nothing),
  the survivors reshard, and the freed host spawns a serving replica
  (``ServingEngine.add_replica``).
- **reclaim** (serve -> train): the learner is the bottleneck
  (rollouts idle: sustained queue depth at or below
  ``DLROVER_TPU_FLYWHEEL_RECLAIM_Q`` while lent chips are out).  One
  replica drains (its in-flight requests requeue onto survivors
  exactly-once), and the rank rejoins the training mesh at the next
  rendezvous.

Decisions ride the PR-10 Brain discipline wholesale: sustain streaks
(one noisy snapshot is not a verdict), a post-execution cooldown,
2x-cooldown hysteresis against lend/reclaim flapping, at most one
in-flight action, and full ``export_state``/``restore_state``
round-tripping so a master failover resumes (or safely abandons) the
action instead of re-deciding it.  Every decision/execution emits the
``scale_decision``/``scale_execute`` instants with ``plane="serve"``
— the classic Brain loop emits ``plane="train"`` — so one chaos trace
shows both planes' verdicts side by side.
"""

import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Callable, Dict, Optional

from dlrover_tpu.common.env import (
    flywheel_lend_queue_depth,
    flywheel_min_train_world,
    flywheel_reclaim_queue_depth,
)
from dlrover_tpu.common.log import default_logger as logger

ACTION_LEND = "lend"
ACTION_RECLAIM = "reclaim"


@dataclass
class FlywheelSignals:
    """One arbitration cycle's view of both planes."""

    #: serving dispatch-queue depth (requests parked waiting for a
    #: replica slot) — the rollout-bound signal
    queue_depth: int = 0
    #: live serving replicas
    serve_replicas: int = 1
    #: trainer data-parallel world size
    train_world: int = 1
    #: trajectories waiting in the trainer's replay buffer (a starved
    #: learner has compute parked on an empty buffer)
    buffer_ready: int = 0


@dataclass
class FlywheelDecision:
    action: str
    reason: str
    from_world: int
    to_world: int
    from_replicas: int
    to_replicas: int
    decision_id: int = 0
    made_at: float = field(default_factory=time.time)

    def to_dict(self) -> Dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: Dict) -> "FlywheelDecision":
        known = {
            k: v for k, v in d.items()
            if k in cls.__dataclass_fields__
        }
        return cls(**known)


class FlywheelArbiter:
    """The rule engine: ``decide()`` turns one cycle's signals into at
    most ONE lend/reclaim decision, under sustain/cooldown/hysteresis.
    All mutable state round-trips through ``export_state`` /
    ``restore_state`` (the journal component contract)."""

    def __init__(
        self,
        lend_q: Optional[float] = None,
        reclaim_q: Optional[float] = None,
        min_train_world: Optional[int] = None,
        sustain_cycles: int = 3,
        cooldown_s: float = 30.0,
        hysteresis_factor: float = 2.0,
    ):
        self.lend_q = (
            flywheel_lend_queue_depth() if lend_q is None else lend_q
        )
        self.reclaim_q = (
            flywheel_reclaim_queue_depth()
            if reclaim_q is None else reclaim_q
        )
        self.min_train_world = (
            flywheel_min_train_world()
            if min_train_world is None else max(int(min_train_world), 1)
        )
        self.sustain_cycles = max(int(sustain_cycles), 1)
        self.cooldown_s = float(cooldown_s)
        self.hysteresis_factor = float(hysteresis_factor)
        self._lend_streak = 0
        self._reclaim_streak = 0
        #: replicas currently running on lent trainer chips — reclaim
        #: only ever takes back what lend gave
        self._lent = 0
        self._last: Optional[FlywheelDecision] = None
        self._in_flight: Optional[FlywheelDecision] = None
        self._next_id = 1
        self._lock = threading.RLock()

    # ------------------------------------------------------------ state
    @property
    def in_flight(self) -> Optional[FlywheelDecision]:
        with self._lock:
            return self._in_flight

    @property
    def lent(self) -> int:
        with self._lock:
            return self._lent

    def complete(self, outcome: str, now: Optional[float] = None):
        """The executor finished (or abandoned) the in-flight action;
        it becomes the cooldown anchor, and the lent-chip ledger
        moves only on a DONE outcome."""
        with self._lock:
            if self._in_flight is None:
                return
            done = self._in_flight
            if outcome == "done":
                if done.action == ACTION_LEND:
                    self._lent += 1
                elif done.action == ACTION_RECLAIM:
                    self._lent = max(self._lent - 1, 0)
            # cooldown runs from COMPLETION, not decision time
            done.made_at = now if now is not None else time.time()
            self._last = done
            self._in_flight = None

    def _cooled_down(self, action: str, now: float) -> bool:
        if self._last is None:
            return True
        quiet = self.cooldown_s
        if self._last.action != action:
            # direction flip pays the hysteresis surcharge
            quiet *= self.hysteresis_factor
        return now - self._last.made_at >= quiet

    # ----------------------------------------------------------- decide
    def decide(self, signals: FlywheelSignals,
               now: Optional[float] = None,
               ) -> Optional[FlywheelDecision]:
        now = time.time() if now is None else now
        with self._lock:
            if self._in_flight is not None:
                return None  # one planned action at a time
            per_replica = signals.queue_depth / max(
                signals.serve_replicas, 1
            )
            if per_replica > self.lend_q:
                self._lend_streak += 1
            else:
                self._lend_streak = 0
            if (
                per_replica <= self.reclaim_q
                and self._lent > 0
            ):
                self._reclaim_streak += 1
            else:
                self._reclaim_streak = 0
            decision = None
            if (
                self._lend_streak >= self.sustain_cycles
                and signals.train_world > self.min_train_world
                and self._cooled_down(ACTION_LEND, now)
            ):
                decision = FlywheelDecision(
                    action=ACTION_LEND,
                    reason=(
                        f"rollout_bound queue/replica "
                        f"{per_replica:.1f} > {self.lend_q:g} "
                        f"x{self._lend_streak}"
                    ),
                    from_world=signals.train_world,
                    to_world=signals.train_world - 1,
                    from_replicas=signals.serve_replicas,
                    to_replicas=signals.serve_replicas + 1,
                )
            elif (
                self._reclaim_streak >= self.sustain_cycles
                and signals.serve_replicas > 1
                and self._cooled_down(ACTION_RECLAIM, now)
            ):
                decision = FlywheelDecision(
                    action=ACTION_RECLAIM,
                    reason=(
                        f"learner_bound queue/replica "
                        f"{per_replica:.1f} <= {self.reclaim_q:g} "
                        f"x{self._reclaim_streak}"
                    ),
                    from_world=signals.train_world,
                    to_world=signals.train_world + 1,
                    from_replicas=signals.serve_replicas,
                    to_replicas=signals.serve_replicas - 1,
                )
            if decision is None:
                return None
            decision.decision_id = self._next_id
            decision.made_at = now
            self._next_id += 1
            self._in_flight = decision
            self._lend_streak = 0
            self._reclaim_streak = 0
            return decision

    # ---------------------------------------------------------- journal
    def export_state(self) -> Dict:
        with self._lock:
            return {
                "lend_streak": self._lend_streak,
                "reclaim_streak": self._reclaim_streak,
                "lent": self._lent,
                "next_id": self._next_id,
                "last": (
                    self._last.to_dict() if self._last else None
                ),
                "in_flight": (
                    self._in_flight.to_dict()
                    if self._in_flight else None
                ),
            }

    def restore_state(self, state: Dict):
        with self._lock:
            self._lend_streak = int(state.get("lend_streak", 0))
            self._reclaim_streak = int(
                state.get("reclaim_streak", 0)
            )
            self._lent = int(state.get("lent", 0))
            self._next_id = int(state.get("next_id", 1))
            last = state.get("last")
            self._last = (
                FlywheelDecision.from_dict(last) if last else None
            )
            inflight = state.get("in_flight")
            self._in_flight = (
                FlywheelDecision.from_dict(inflight)
                if inflight else None
            )


class FlywheelOperator:
    """The executing shell around :class:`FlywheelArbiter`: consumes
    both planes' gauges, executes at most one decision per
    ``evaluate`` through caller-supplied ``lend_fn`` / ``reclaim_fn``
    (the harness wires these to the actual drain + ``add_replica`` /
    ``drain_replica`` + rejoin machinery), journals every transition,
    and emits the plane-labeled timeline instants."""

    def __init__(
        self,
        lend_fn: Callable[[FlywheelDecision], bool],
        reclaim_fn: Callable[[FlywheelDecision], bool],
        arbiter: Optional[FlywheelArbiter] = None,
    ):
        self._lend_fn = lend_fn
        self._reclaim_fn = reclaim_fn
        self.arbiter = arbiter or FlywheelArbiter()
        self._journal_cb: Optional[Callable[[str, Dict], None]] = None

    def set_journal(self, cb: Optional[Callable[[str, Dict], None]]):
        """Journal sink (the PR-7 ControlPlaneJournal contract): every
        decision/outcome appends a row, and the current arbiter state
        snapshots so a failed-over master resumes mid-action."""
        self._journal_cb = cb

    def _journal(self, kind: str, payload: Dict):
        if self._journal_cb is not None:
            self._journal_cb(kind, payload)
            self._journal_cb("state", self.arbiter.export_state())

    def export_state(self) -> Dict:
        return self.arbiter.export_state()

    def restore_state(self, state: Dict):
        self.arbiter.restore_state(state)

    @staticmethod
    def _labels(decision: FlywheelDecision) -> Dict:
        return dict(
            action=decision.action,
            reason=decision.reason,
            from_world=decision.from_world,
            to_world=decision.to_world,
            plane="serve",
            from_replicas=decision.from_replicas,
            to_replicas=decision.to_replicas,
            decision_id=decision.decision_id,
        )

    def _emit_decision(self, decision: FlywheelDecision):
        from dlrover_tpu.observability.events import get_event_logger

        get_event_logger().instant(
            "scale_decision", **self._labels(decision)
        )

    def _emit_execute(self, decision: FlywheelDecision, outcome: str):
        from dlrover_tpu.observability.events import get_event_logger

        get_event_logger().instant(
            "scale_execute", outcome=outcome, **self._labels(decision)
        )

    def resume_in_flight(self) -> Optional[str]:
        """A failed-over master found an in-flight action in the
        restored state: re-execute it under the SAME decision id (the
        lend/reclaim callbacks are idempotent drains) instead of
        re-deciding."""
        decision = self.arbiter.in_flight
        if decision is None:
            return None
        return self._execute(decision)

    def _execute(self, decision: FlywheelDecision) -> str:
        fn = (
            self._lend_fn
            if decision.action == ACTION_LEND else self._reclaim_fn
        )
        try:
            ok = bool(fn(decision))
            outcome = "done" if ok else "abandoned"
        except Exception as e:  # noqa: BLE001 - an executor crash
            # must not wedge arbitration forever
            logger.error(
                "flywheel %s execution failed: %s",
                decision.action, e,
            )
            outcome = "abandoned"
        self.arbiter.complete(outcome)
        self._emit_execute(decision, outcome)
        self._journal(
            "execute",
            {**decision.to_dict(), "outcome": outcome},
        )
        return outcome

    def evaluate(self, signals: FlywheelSignals,
                 now: Optional[float] = None) -> Optional[str]:
        """One arbitration cycle: decide (maybe), execute, journal.
        Returns the execution outcome or None (no action)."""
        decision = self.arbiter.decide(signals, now=now)
        if decision is None:
            return None
        self._emit_decision(decision)
        self._journal("decision", decision.to_dict())
        return self._execute(decision)
