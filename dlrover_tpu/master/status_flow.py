"""Legal node status-machine transitions.

Reference parity: ``dlrover/python/master/node/status_flow.py:27``
(``NODE_STATE_FLOWS``).
"""

from dataclasses import dataclass

from dlrover_tpu.common.constants import NodeStatus


@dataclass(frozen=True)
class NodeStateFlow:
    from_status: str
    to_status: str
    should_relaunch: bool = False


ALLOWED_TRANSITIONS = {
    (NodeStatus.INITIAL, NodeStatus.PENDING),
    (NodeStatus.INITIAL, NodeStatus.RUNNING),
    (NodeStatus.INITIAL, NodeStatus.FAILED),
    (NodeStatus.INITIAL, NodeStatus.DELETED),
    (NodeStatus.PENDING, NodeStatus.RUNNING),
    (NodeStatus.PENDING, NodeStatus.SUCCEEDED),
    (NodeStatus.PENDING, NodeStatus.FAILED),
    (NodeStatus.PENDING, NodeStatus.DELETED),
    (NodeStatus.RUNNING, NodeStatus.SUCCEEDED),
    (NodeStatus.RUNNING, NodeStatus.FAILED),
    (NodeStatus.RUNNING, NodeStatus.DELETED),
    (NodeStatus.RUNNING, NodeStatus.BREAKDOWN),
    (NodeStatus.SUCCEEDED, NodeStatus.DELETED),
    (NodeStatus.FAILED, NodeStatus.DELETED),
    (NodeStatus.BREAKDOWN, NodeStatus.DELETED),
}

_RELAUNCH_TRIGGERS = {NodeStatus.FAILED, NodeStatus.DELETED,
                      NodeStatus.BREAKDOWN}


def get_node_state_flow(from_status: str, to_status: str):
    """Return the flow if legal else None; flags whether the transition
    is a relaunch trigger (failure-ish end state from a live state)."""
    if from_status == to_status:
        return None
    if (from_status, to_status) not in ALLOWED_TRANSITIONS:
        return None
    should_relaunch = (
        to_status in _RELAUNCH_TRIGGERS
        and from_status
        in (NodeStatus.INITIAL, NodeStatus.PENDING, NodeStatus.RUNNING)
    )
    return NodeStateFlow(from_status, to_status, should_relaunch)
