"""Node watchers: cluster events -> NodeEvents for the job manager.

Reference parity: ``dlrover/python/master/watcher/`` — ``PodWatcher``
(``k8s_watcher.py``: list/watch pods, map phases to NodeStatus) and the
base watcher.  The client is injected (tests use fakes, per the
reference's own strategy).
"""

import threading
from abc import ABCMeta, abstractmethod
from typing import Callable, List, Optional

from dlrover_tpu.common.constants import NodeEventType, NodeStatus
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.node import Node
from dlrover_tpu.master.job_manager import NodeEvent

_POD_PHASE_TO_STATUS = {
    "Pending": NodeStatus.PENDING,
    "Running": NodeStatus.RUNNING,
    "Succeeded": NodeStatus.SUCCEEDED,
    "Failed": NodeStatus.FAILED,
    "Unknown": NodeStatus.UNKNOWN,
}


def pod_phase_to_status(phase: str) -> str:
    return _POD_PHASE_TO_STATUS.get(phase, NodeStatus.UNKNOWN)


class NodeWatcher(metaclass=ABCMeta):
    @abstractmethod
    def list(self) -> List[Node]:
        ...

    @abstractmethod
    def watch(self, handler: Callable[[NodeEvent], None]):
        """Blocking watch loop, one NodeEvent per cluster change."""


class PodWatcher(NodeWatcher):
    """k8s pod list/watch → NodeEvent (reference ``k8s_watcher.py``)."""

    def __init__(self, job_name: str, k8s_client=None):
        if k8s_client is None:
            from dlrover_tpu.scheduler.kubernetes import k8sClient

            k8s_client = k8sClient.singleton_instance()
        self._client = k8s_client
        self._job_name = job_name
        self._selector = f"job={job_name}"
        self._stopped = threading.Event()

    def _pod_to_node(self, pod) -> Optional[Node]:
        meta = pod.metadata
        labels = meta.labels or {}
        try:
            node_id = int(labels.get("node-id", "-1"))
        except ValueError:
            return None
        if node_id < 0:
            return None
        node = Node(
            node_type=labels.get("node-type", "worker"),
            node_id=node_id,
            name=meta.name,
            status=pod_phase_to_status(pod.status.phase),
        )
        if pod.status.phase == "Failed":
            # exit reason from the first terminated container
            statuses = pod.status.container_statuses or []
            for cs in statuses:
                term = cs.state and cs.state.terminated
                if term:
                    node.exit_reason = term.reason or ""
                    break
        return node

    def list(self) -> List[Node]:
        pods = self._client.list_pods(self._selector)
        nodes = []
        for pod in pods.items:
            node = self._pod_to_node(pod)
            if node:
                nodes.append(node)
        return nodes

    def watch(self, handler: Callable[[NodeEvent], None]):
        backoff = 1.0
        while not self._stopped.is_set():
            try:
                for raw in self._client.watch_pods(self._selector):
                    if self._stopped.is_set():
                        return
                    backoff = 1.0  # stream is healthy
                    node = self._pod_to_node(raw["object"])
                    if node is None:
                        continue
                    etype = {
                        "ADDED": NodeEventType.ADDED,
                        "MODIFIED": NodeEventType.MODIFIED,
                        "DELETED": NodeEventType.DELETED,
                    }.get(raw["type"], NodeEventType.MODIFIED)
                    handler(NodeEvent(etype, node))
            except Exception as e:  # noqa: BLE001
                logger.warning(
                    "pod watch interrupted: %s; retry in %.0fs",
                    e,
                    backoff,
                )
                if self._stopped.wait(backoff):
                    return
                backoff = min(backoff * 2, 60.0)

    def stop(self):
        self._stopped.set()


class FakeWatcher(NodeWatcher):
    """Test double: events pushed programmatically."""

    def __init__(self, nodes: Optional[List[Node]] = None):
        self._nodes = nodes or []
        self._handler = None

    def list(self) -> List[Node]:
        return list(self._nodes)

    def watch(self, handler):
        self._handler = handler

    def push(self, event: NodeEvent):
        if self._handler:
            self._handler(event)
