"""Node lifecycle supervision inside the master.

Reference parity: ``dlrover/python/master/node/dist_job_manager.py`` and
``local_job_manager.py`` — the job manager owns the node table, consumes
node events (from the agent heartbeats locally, or a pod watcher on
k8s), decides relaunches, and feeds the speed monitor / rendezvous
managers through event callbacks.
"""

import os
import threading
import time
from abc import ABCMeta, abstractmethod
from typing import Callable, Dict, List, Optional

from dlrover_tpu.common.constants import (
    NodeEventType,
    NodeExitReason,
    NodeStatus,
    NodeType,
    TrainingExceptionLevel,
)
from dlrover_tpu.common.global_context import Context
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.messages import ParallelConfig
from dlrover_tpu.common.node import Node, NodeResource
from dlrover_tpu.master.status_flow import get_node_state_flow

_ctx = Context.singleton_instance()


class NodeEvent:
    def __init__(self, event_type: str, node: Node):
        self.event_type = event_type
        self.node = node


class NodeEventCallback(metaclass=ABCMeta):
    """Hooks invoked on node status transitions (reference:
    ``master/node/event_callback.py:42``)."""

    def on_node_started(self, node: Node, cluster_context):
        ...

    def on_node_succeeded(self, node: Node, cluster_context):
        ...

    def on_node_failed(self, node: Node, cluster_context):
        ...

    def on_node_deleted(self, node: Node, cluster_context):
        ...


class TaskRescheduleCallback(NodeEventCallback):
    """Recover the data shards of a dead worker (reference ``:111``)."""

    def __init__(self, task_manager):
        self._task_manager = task_manager

    def on_node_failed(self, node: Node, cluster_context):
        self._task_manager.recover_tasks(node.id)

    def on_node_deleted(self, node: Node, cluster_context):
        self._task_manager.recover_tasks(node.id)


class AllReduceNodeHandlingCallback(NodeEventCallback):
    """Bookkeeping for SPMD training: update the speed monitor and drop
    dead nodes from pending rendezvous (reference ``:218``)."""

    def __init__(self, master):
        self._master = master

    def on_node_started(self, node: Node, cluster_context):
        if node.type == NodeType.WORKER:
            self._master.speed_monitor.add_running_worker(
                node.type, node.id
            )

    def on_node_succeeded(self, node: Node, cluster_context):
        self._master.speed_monitor.remove_running_worker(
            node.type, node.id
        )

    def on_node_failed(self, node: Node, cluster_context):
        self._master.speed_monitor.remove_running_worker(
            node.type, node.id
        )
        for manager in self._master.rdzv_managers.values():
            manager.remove_alive_node(node.rank_index)

    def on_node_deleted(self, node: Node, cluster_context):
        self.on_node_failed(node, cluster_context)


class JobManager(metaclass=ABCMeta):
    def __init__(self):
        self._lock = threading.Lock()
        self._nodes: Dict[int, Node] = {}
        self._event_callbacks: List[NodeEventCallback] = []
        self._stopped = False
        self._paral_config = ParallelConfig()
        self._restart_verdicts: Dict[int, bool] = {}
        from dlrover_tpu.master.error_monitor import ErrorMonitor
        from dlrover_tpu.master.node_managers import NodeGroupRegistry

        self._error_monitor = ErrorMonitor()
        self._node_groups = NodeGroupRegistry()
        self._stop_reason: Optional[str] = None
        #: bumped on every node-table change; the ``RunningNodes``
        #: delta protocol's version (NotModified when it matches)
        self._nodes_version = 0
        #: failover journal hook: node-table changes journal the
        #: changed node's row (replay is per-node last-writer-wins)
        self._journal_cb: Optional[Callable[[str, dict], None]] = None

    def set_journal(self, cb: Optional[Callable[[str, dict], None]]):
        self._journal_cb = cb

    @property
    def nodes_version(self) -> int:
        return self._nodes_version

    def _bump_nodes_version(self):
        self._nodes_version += 1

    @staticmethod
    def _node_row(node: Node) -> dict:
        return {
            "type": node.type,
            "id": node.id,
            "rank_index": node.rank_index,
            "status": node.status,
            "host_addr": node.host_addr,
            "relaunch_count": node.relaunch_count,
            "max_relaunch_count": node.max_relaunch_count,
            "create_time": node.create_time,
            "heartbeat_time": node.heartbeat_time,
            "is_released": node.is_released,
            "exit_reason": node.exit_reason,
        }

    def _journal_node(self, node: Node):
        if self._journal_cb is None:
            return
        try:
            self._journal_cb("node", self._node_row(node))
        except Exception as e:  # noqa: BLE001
            logger.warning("node journal failed: %s", e)

    @property
    def error_monitor(self):
        return self._error_monitor

    @property
    def node_groups(self):
        return self._node_groups

    def should_stop_job(self) -> Optional[str]:
        """Non-None when a failure classification or a critical node
        group decided the job cannot continue (checked by the master's
        supervision loop)."""
        return self._stop_reason

    def add_node_event_callback(self, callback: NodeEventCallback):
        self._event_callbacks.append(callback)

    @abstractmethod
    def start(self):
        ...

    def stop(self):
        self._stopped = True

    # -- node table --------------------------------------------------------
    @property
    def nodes(self) -> Dict[int, Node]:
        return self._nodes

    def get_node(self, node_id: int) -> Optional[Node]:
        return self._nodes.get(node_id)

    def get_running_nodes(self) -> List[Node]:
        with self._lock:
            return [
                n
                for n in self._nodes.values()
                if n.status == NodeStatus.RUNNING
            ]

    def all_workers_exited(self) -> bool:
        with self._lock:
            if not self._nodes:
                return False
            return all(
                n.status in NodeStatus.end_states()
                for n in self._nodes.values()
            )

    def all_workers_failed(self) -> bool:
        with self._lock:
            if not self._nodes:
                return False
            return all(
                n.status in (NodeStatus.FAILED, NodeStatus.BREAKDOWN)
                for n in self._nodes.values()
            )

    # -- events ------------------------------------------------------------
    def process_event(self, event: NodeEvent):
        new_status = event.node.status
        with self._lock:
            node = self._nodes.get(event.node.id)
            if node is None:
                # first sighting: insert and treat the reported status as
                # a transition from INITIAL so callbacks still fire
                node = event.node
                self._nodes[node.id] = node
                node.update_status(new_status)
                if event.event_type == NodeEventType.DELETED:
                    node.is_released = True
                fire = new_status != NodeStatus.INITIAL
            else:
                flow = get_node_state_flow(node.status, new_status)
                if flow is None:
                    return
                node.update_status(new_status)
                if event.event_type == NodeEventType.DELETED:
                    node.is_released = True
                fire = True
            self._node_groups.route(node)
            self._bump_nodes_version()
            self._journal_node(node)
        if fire:
            self._fire_callbacks(node, new_status)

    def _fire_callbacks(self, node: Node, status: str):
        for callback in self._event_callbacks:
            try:
                if status == NodeStatus.RUNNING:
                    callback.on_node_started(node, None)
                elif status == NodeStatus.SUCCEEDED:
                    callback.on_node_succeeded(node, None)
                elif status in (NodeStatus.FAILED, NodeStatus.BREAKDOWN):
                    callback.on_node_failed(node, None)
                elif status == NodeStatus.DELETED:
                    callback.on_node_deleted(node, None)
            except Exception as e:  # noqa: BLE001
                logger.error("node event callback error: %s", e)

    # -- agent-facing state ------------------------------------------------
    def update_node_resource_usage(self, node_type: str, node_id: int,
                                   cpu: float, memory: int,
                                   tpu_stats=None):
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None:
                return
            node.used_resource = NodeResource(cpu=cpu, memory=memory)
            if tpu_stats:
                node.used_resource.tpu_chips = len(tpu_stats)
            # deliberately NO version bump: resource ticks arrive from
            # every node every ~15 s, so bumping here would defeat the
            # NotModified delta protocol exactly at fleet scale.  The
            # version tracks MEMBERSHIP (status/address/insert);
            # resource freshness over the versioned path is
            # best-effort until the next membership change.

    def update_node_address(self, node_type: str, node_id: int, addr: str):
        with self._lock:
            node = self._nodes.setdefault(
                node_id,
                Node(node_type, node_id, status=NodeStatus.RUNNING),
            )
            node.host_addr = addr
            self._bump_nodes_version()
            self._journal_node(node)

    def collect_node_heartbeat(self, node_type: str, node_id: int,
                               timestamp: float):
        started = False
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None:
                node = Node(node_type, node_id,
                            status=NodeStatus.RUNNING)
                self._nodes[node_id] = node
                started = True
            node.heartbeat_time = timestamp
            if node.status == NodeStatus.INITIAL:
                node.update_status(NodeStatus.RUNNING)
                started = True
            if started:
                self._bump_nodes_version()
                self._journal_node(node)
        if started:
            self._fire_callbacks(node, NodeStatus.RUNNING)

    def handle_training_failure(self, node_type: str, node_id: int,
                                restart_count: int, error_data: str,
                                level: str):
        logger.warning(
            "training failure on %s-%s (restart %s, level %s): %s",
            node_type, node_id, restart_count, level, error_data,
        )
        # durable audit trail (Brain datastore node-event recorder)
        from dlrover_tpu.master.datastore import get_default_datastore

        store = get_default_datastore()
        if store is not None:
            try:
                store.record_node_event(
                    os.getenv("DLROVER_TPU_JOB_NAME", "default"),
                    f"{node_type}-{node_id}",
                    level,
                    error_data[:512],
                )
            except Exception as e:  # noqa: BLE001
                logger.warning("node-event persist failed: %s", e)
        # classify the failure and record the recommended recovery
        # rung (error monitor — ref monitor/error_monitor.py)
        action = None
        if self._error_monitor is not None:
            action = self._error_monitor.report(
                node_id, node_type, error_data
            )
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None:
                return
            if level in (
                TrainingExceptionLevel.NODE_ERROR,
                TrainingExceptionLevel.NODE_PREEMPTED,
            ):
                # a preempted node is hardware-gone like a failed one
                # (relaunch verdict set so the controller replaces
                # it); the rendezvous fencing rides the servicer path
                node.set_exit_reason(NodeExitReason.HARDWARE_ERROR)
                self._restart_verdicts[node_id] = True
            elif level == TrainingExceptionLevel.NODE_EXCLUDED:
                # a scheduling verdict the master itself issued: audit
                # trail only — no relaunch verdict, no error-monitor
                # escalation (the node is healthy, just unwanted)
                pass
            elif action is not None:
                from dlrover_tpu.master.error_monitor import (
                    RecoveryAction,
                )

                if action == RecoveryAction.RELAUNCH_NODE:
                    node.set_exit_reason(NodeExitReason.HARDWARE_ERROR)
                    self._restart_verdicts[node_id] = True
                elif action == RecoveryAction.GROW_MEMORY:
                    node.set_exit_reason(NodeExitReason.OOM)
                    self._restart_verdicts[node_id] = True
                elif action == RecoveryAction.STOP_JOB:
                    # deterministic user-code failure: burning the
                    # relaunch budget on it wastes cluster time
                    self._stop_reason = (
                        f"node {node_id}: repeated user-code failure"
                    )
            # critical-group accounting (chief semantics)
            self._node_groups.route(node)
            self._journal_node(node)
            if self._node_groups.job_should_stop(node):
                self._stop_reason = (
                    f"critical {node.type} node {node_id} exhausted "
                    "its relaunch budget"
                )

    def should_restart_node(self, node_type: str, node_id: int) -> bool:
        return self._restart_verdicts.pop(node_id, False)

    def apply_diagnosis_conclusions(self, conclusions):
        """Act on inference-chain conclusions (master/diagnosis.py):
        restart_process / relaunch_node set the per-node restart
        verdict that agents poll via CheckHardwareResetRequest."""
        with self._lock:
            for c in conclusions:
                if c.action not in ("restart_process", "relaunch_node"):
                    continue
                targets = (
                    [c.node_rank]
                    if c.node_rank >= 0
                    else list(self._nodes)
                )
                for node_id in targets:
                    node = self._nodes.get(node_id)
                    if node is None:
                        continue
                    if c.action == "relaunch_node":
                        node.set_exit_reason(
                            NodeExitReason.HARDWARE_ERROR
                        )
                    self._restart_verdicts[node_id] = True
                logger.info(
                    "diagnosis %s (%s) -> %s nodes %s",
                    c.problem, c.cause, c.action, targets,
                )

    def update_paral_config(self, config: ParallelConfig):
        self._paral_config = config

    def get_paral_config(self) -> ParallelConfig:
        return self._paral_config

    # --------------------------------------------- failover replay
    def export_state(self) -> dict:
        with self._lock:
            return {
                "nodes": [
                    self._node_row(n) for n in self._nodes.values()
                ],
                "version": self._nodes_version,
            }

    def _install_node_row(self, row: dict):
        """Caller holds the lock: upsert one journaled node row."""
        node_id = int(row.get("id", 0))
        node = self._nodes.get(node_id)
        if node is None:
            node = Node(
                row.get("type", NodeType.WORKER),
                node_id,
                rank_index=row.get("rank_index"),
                status=row.get("status", NodeStatus.INITIAL),
                max_relaunch_count=int(
                    row.get("max_relaunch_count", 3)
                ),
            )
            self._nodes[node_id] = node
        else:
            node.status = row.get("status", node.status)
        node.host_addr = row.get("host_addr", "") or node.host_addr
        node.relaunch_count = int(row.get("relaunch_count", 0))
        node.create_time = row.get("create_time")
        node.heartbeat_time = float(row.get("heartbeat_time", 0) or 0)
        node.is_released = bool(row.get("is_released", False))
        if row.get("exit_reason"):
            node.set_exit_reason(row["exit_reason"])
        self._node_groups.route(node)

    def restore_state(self, state: dict):
        """Install a snapshotted node table (replay path — not
        re-journaled; no callbacks fire: the nodes already HAD their
        transitions under the previous incarnation)."""
        with self._lock:
            cb, self._journal_cb = self._journal_cb, None
            try:
                for row in state.get("nodes") or []:
                    self._install_node_row(row)
            finally:
                self._journal_cb = cb
            self._nodes_version = max(
                self._nodes_version, int(state.get("version", 0))
            )
            # relaunch ids must not collide with restored nodes
            if hasattr(self, "_next_node_id") and self._nodes:
                self._next_node_id = max(
                    self._next_node_id, max(self._nodes) + 1
                )

    def apply_journal_op(self, op: str, args: dict):
        if op == "node":
            with self._lock:
                self._install_node_row(args)
                self._bump_nodes_version()


class LocalJobManager(JobManager):
    """Single-host job manager used by the local master that
    ``dlrover-tpu-run`` spawns (reference:
    ``master/node/local_job_manager.py``)."""

    def __init__(self, node_num: int = 1):
        super().__init__()
        self._node_num = node_num

    def start(self):
        for node_id in range(self._node_num):
            # setdefault: a failover replay may already have restored
            # this node's row — the fresh INITIAL placeholder must not
            # clobber it
            self._nodes.setdefault(
                node_id,
                Node(
                    NodeType.WORKER,
                    node_id,
                    status=NodeStatus.INITIAL,
                    max_relaunch_count=_ctx.max_node_relaunch_times,
                ),
            )

    def has_job_error(self) -> bool:
        return False


class DistributedJobManager(JobManager):
    """Multi-host job manager: supervises heartbeats and relaunches
    through a pluggable scaler (reference:
    ``master/node/dist_job_manager.py:80``).  The k8s watcher/scaler
    plug in here; in in-process tests a fake scaler is injected.
    """

    def __init__(self, node_num: int, scaler=None,
                 heartbeat_timeout: Optional[float] = None,
                 pending_timeout: Optional[float] = None):
        super().__init__()
        self._node_num = node_num
        self._scaler = scaler
        self._heartbeat_timeout = (
            heartbeat_timeout or _ctx.node_heartbeat_timeout
        )
        self._pending_timeout = (
            pending_timeout or _ctx.pending_timeout_secs
        )
        self._next_node_id = node_num

    def start(self):
        for node_id in range(self._node_num):
            if node_id in self._nodes:
                continue  # restored by a failover replay
            node = Node(
                NodeType.WORKER,
                node_id,
                status=NodeStatus.INITIAL,
                max_relaunch_count=_ctx.max_node_relaunch_times,
            )
            node.create_time = time.time()
            self._nodes[node_id] = node
        if self._scaler is not None:
            self._scaler.scale_to(self._node_num)
        threading.Thread(
            target=self._monitor_heartbeats,
            name="heartbeat-monitor",
            daemon=True,
        ).start()

    def _monitor_heartbeats(self):
        while not self._stopped:
            self.check_dead_nodes()
            time.sleep(15)

    def check_dead_nodes(self) -> List[Node]:
        """Mark heartbeat-timed-out and pending-timed-out nodes failed
        and decide relaunch.  The pending check catches nodes that never
        sent a single heartbeat (crashlooping before the agent starts)."""
        dead = []
        now = time.time()
        with self._lock:
            for node in list(self._nodes.values()):
                hb_dead = node.timeout(self._heartbeat_timeout)
                pend_dead = (
                    node.status
                    in (NodeStatus.INITIAL, NodeStatus.PENDING)
                    and node.create_time is not None
                    and now - node.create_time > self._pending_timeout
                )
                if hb_dead or pend_dead:
                    node.update_status(NodeStatus.FAILED)
                    node.set_exit_reason(NodeExitReason.KILLED)
                    dead.append(node)
        for node in dead:
            logger.warning(
                "node %s dead (heartbeat/pending timeout); failed",
                node.id,
            )
            self._fire_callbacks(node, NodeStatus.FAILED)
            self._maybe_relaunch(node)
        return dead

    def _maybe_relaunch(self, node: Node):
        if node.is_unrecoverable_failure():
            logger.error(
                "node %s is unrecoverable (relaunch %s/%s, reason=%s)",
                node.id, node.relaunch_count,
                node.max_relaunch_count, node.exit_reason,
            )
            return
        node.inc_relaunch_count()
        with self._lock:
            new_node = node.get_relaunch_node(self._next_node_id)
            new_node.create_time = time.time()
            self._next_node_id += 1
            self._nodes[new_node.id] = new_node
        logger.info(
            "relaunching node %s as node %s", node.id, new_node.id
        )
        if self._scaler is not None:
            self._scaler.relaunch(node, new_node)
