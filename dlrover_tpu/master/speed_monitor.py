"""Training-speed monitor: global-step samples -> steps/sec, hang and
straggler signals.

Reference parity: ``dlrover/python/master/monitor/speed_monitor.py:43,
81,113``.
"""

import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from dlrover_tpu.common.global_context import Context

_ctx = Context.singleton_instance()


class GlobalStepRecord:
    def __init__(self, global_step: int, timestamp: float, worker_num: int):
        self.global_step = global_step
        self.timestamp = timestamp
        self.worker_num = worker_num


class SpeedMonitor:
    def __init__(self, record_num: Optional[int] = None):
        self._lock = threading.Lock()
        self._max_record_count = record_num or _ctx.train_speed_record_num
        self._global_step_records: List[GlobalStepRecord] = []
        self._workers: Set[Tuple[str, int]] = set()
        self._global_step = 0
        self._target_worker_num = 0
        self._init_time = time.time()
        self._start_training_time = 0.0

    def set_target_worker_num(self, worker_num: int):
        self._target_worker_num = worker_num

    def reduce_target_worker_num(self, workers):
        with self._lock:
            removed = sum(1 for w in workers if w in self._workers)
            self._target_worker_num = max(
                self._target_worker_num - removed, 0
            )

    def add_running_worker(self, node_type: str, worker_id: int):
        with self._lock:
            self._workers.add((node_type, worker_id))

    def remove_running_worker(self, node_type: str, worker_id: int):
        with self._lock:
            self._workers.discard((node_type, worker_id))

    @property
    def running_workers(self):
        return self._workers

    def set_start_timestamp(self):
        if self._global_step == 0 and not self._global_step_records:
            self._start_training_time = time.time()

    def collect_global_step(self, global_step: int, timestamp: float):
        with self._lock:
            if not self._start_training_time:
                self._start_training_time = time.time()
            self._global_step = global_step
            self._global_step_records.append(
                GlobalStepRecord(
                    global_step, timestamp, len(self._workers)
                )
            )
            if len(self._global_step_records) > self._max_record_count:
                self._global_step_records.pop(0)

    @property
    def completed_global_step(self) -> int:
        return self._global_step

    @property
    def start_training_time(self) -> float:
        return self._start_training_time

    def running_speed(self) -> float:
        """Steps/sec over the last two samples (reference ``:113``)."""
        with self._lock:
            if len(self._global_step_records) < 2:
                return 0.0
            last, prev = (
                self._global_step_records[-1],
                self._global_step_records[-2],
            )
            dt = last.timestamp - prev.timestamp
            if dt <= 0:
                return 0.0
            return (last.global_step - prev.global_step) / dt

    def worker_adjustment_finished(self) -> bool:
        """True when the sampled worker count has been stable at the
        target for the whole record window."""
        with self._lock:
            if not self._global_step_records:
                return False
            worker_num = self._global_step_records[-1].worker_num
            if worker_num != self._target_worker_num:
                return False
            return all(
                r.worker_num == worker_num
                for r in self._global_step_records
            )

    def all_worker_joined(self) -> bool:
        with self._lock:
            return (
                self._target_worker_num > 0
                and len(self._workers) == self._target_worker_num
            )

    def step_is_stagnant(self, hang_secs: Optional[float] = None) -> bool:
        """Hang signal: no global-step progress for hang_secs while
        workers are running (feeds the master's hang diagnosis).

        Jobs that never report GlobalStep are NOT flagged — killing a
        healthy job that simply doesn't use step reporting is worse
        than missing a hang (reference gates on
        ``all_running_node_hanged`` + task hang for the same reason)."""
        hang_secs = hang_secs or _ctx.hang_detection_secs
        with self._lock:
            if not self._global_step_records:
                return False
            last = self._global_step_records[-1]
            return time.time() - last.timestamp > hang_secs
