"""Job metrics collection and reporting.

Reference parity: ``dlrover/python/master/stats/`` —
``JobMetricCollector`` (``job_collector.py:185``), ``StatsReporter``
(``reporter.py``: LOCAL vs BRAIN ``ReporterType``) and
``training_metrics.py``.  The local reporter stores in-process (the
brain-backed reporter plugs in through the same interface).
"""

import json
import threading
import time
from abc import ABCMeta, abstractmethod
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

from dlrover_tpu.common.log import default_logger as logger


class ReporterType:
    LOCAL = "local"
    BRAIN = "brain"


@dataclass
class JobMeta:
    job_name: str = ""
    namespace: str = ""
    uuid: str = ""


@dataclass
class RuntimeMetric:
    timestamp: float
    global_step: int
    speed: float
    running_nodes: int
    node_resources: Dict = field(default_factory=dict)


@dataclass
class ModelMetric:
    num_params: int = 0
    flops_per_step: float = 0.0
    hidden_size: int = 0
    num_layers: int = 0
    seq_len: int = 0


class StatsReporter(metaclass=ABCMeta):
    @abstractmethod
    def report_runtime(self, metric: RuntimeMetric):
        ...

    @abstractmethod
    def report_model(self, metric: ModelMetric):
        ...

    @abstractmethod
    def report_job_exit(self, success: bool, reason: str):
        ...


class LocalStatsReporter(StatsReporter):
    """In-memory store, optionally mirrored to a JSONL file for
    offline analysis (the reference's MySQL-less mode)."""

    def __init__(self, job_meta: Optional[JobMeta] = None,
                 dump_path: str = ""):
        self.job_meta = job_meta or JobMeta()
        self.runtime: List[RuntimeMetric] = []
        self.model: Optional[ModelMetric] = None
        self.exit_info: Optional[Dict] = None
        self._dump_path = dump_path
        self._lock = threading.Lock()

    def _dump(self, kind: str, payload: Dict):
        if not self._dump_path:
            return
        try:
            with open(self._dump_path, "a") as f:
                f.write(json.dumps({"kind": kind, **payload}) + "\n")
        except OSError as e:
            logger.warning("stats dump failed: %s", e)

    def report_runtime(self, metric: RuntimeMetric):
        with self._lock:
            self.runtime.append(metric)
            if len(self.runtime) > 4096:
                self.runtime.pop(0)
        self._dump("runtime", asdict(metric))

    def report_model(self, metric: ModelMetric):
        with self._lock:
            self.model = metric
        self._dump("model", asdict(metric))

    def report_job_exit(self, success: bool, reason: str):
        with self._lock:
            self.exit_info = {
                "success": success,
                "reason": reason,
                "timestamp": time.time(),
            }
        self._dump("exit", self.exit_info)


class JobMetricCollector:
    """Aggregates from SpeedMonitor + JobManager into the reporter
    (reference ``job_collector.py``)."""

    def __init__(
        self,
        reporter: StatsReporter,
        speed_monitor=None,
        job_manager=None,
        interval: float = 30.0,
    ):
        self._reporter = reporter
        self._speed_monitor = speed_monitor
        self._job_manager = job_manager
        self._interval = interval
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def collect_model_info(self, num_params: int,
                           flops_per_step: float = 0.0, **kwargs):
        self._reporter.report_model(
            ModelMetric(
                num_params=num_params,
                flops_per_step=flops_per_step,
                **{
                    k: v
                    for k, v in kwargs.items()
                    if k in ("hidden_size", "num_layers", "seq_len")
                },
            )
        )

    def _tick(self):
        step = 0
        speed = 0.0
        if self._speed_monitor is not None:
            step = self._speed_monitor.completed_global_step
            # running_speed is a METHOD (same defect auto_scaler had:
            # the bare attribute serialized a bound method as "speed")
            speed = self._speed_monitor.running_speed()
        running = 0
        resources: Dict = {}
        if self._job_manager is not None:
            nodes = self._job_manager.get_running_nodes()
            running = len(nodes)
            for n in nodes:
                resources[n.name] = {
                    "cpu": n.used_resource.cpu,
                    "memory": n.used_resource.memory,
                }
        self._reporter.report_runtime(
            RuntimeMetric(
                timestamp=time.time(),
                global_step=step,
                speed=speed,
                running_nodes=running,
                node_resources=resources,
            )
        )

    def start(self):
        if self._thread is not None:
            return

        def _loop():
            while not self._stopped.wait(self._interval):
                try:
                    self._tick()
                except Exception as e:  # noqa: BLE001
                    logger.warning("metric collection failed: %s", e)

        self._thread = threading.Thread(
            target=_loop, name="metric-collector", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stopped.set()
