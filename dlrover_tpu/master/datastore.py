"""Durable Brain datastore: cross-restart job/fleet history (sqlite).

Reference parity: the Go Brain persists job metrics to MySQL so
optimization learns across restarts and across jobs
(``dlrover/go/brain/pkg/datastore/``, ``dbbase/recorder.go:280``,
``docs/design/db-design.md``).  The TPU redesign trades the external
DB for an embedded sqlite file: a single-master control plane needs
durability and queryability, not a fleet-shared SQL server — and a
file on the master's persistent volume survives master restarts, which
is the failure mode that matters (VERDICT-r3: "a master restart loses
everything learned").

Three recorders:
- strategy measurements  (workload signature -> (strategy, step time))
  — feeds the strategy service's CalibratedPlanner across restarts
- speed samples          (worker count -> records/sec per job)
  — feeds WorkerResource's marginal-gain decisions
- node events            (failures, OOMs, relaunches per job)
  — the diagnosis/audit trail

High-rate writes (speed samples, node events, timeline batches) are
WRITE-BEHIND by default: recorders enqueue rows on a bounded
in-memory queue and a single background flusher drains them with
per-table ``executemany`` + ONE commit per batch — a timeline burst
costs one fsync instead of one per event, and the report RPC path
never blocks on sqlite.  Readers drain the queue first, so
read-your-writes semantics are preserved exactly.  Strategy
measurements stay synchronous: they are one row per calibration step
and a concurrently-live neighbour master may read the shared file the
moment the recorder returns.  ``close()`` drains
everything and checkpoints the WAL (fsync'd durability), and
``DLROVER_TPU_DATASTORE_SYNC=1`` restores the old synchronous
INSERT+commit-per-write behavior byte-for-byte (pinned by tests).
One lock serializes the shared connection (sqlite's own locking is
per-process anyway).
"""

import json
import os
import sqlite3
import threading
import time
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.common.env import datastore_sync_enabled
from dlrover_tpu.common.log import default_logger as logger

_SCHEMA = """
CREATE TABLE IF NOT EXISTS strategy_measurements (
    workload TEXT NOT NULL,
    strategy TEXT NOT NULL,
    step_time_s REAL NOT NULL,
    created_at REAL NOT NULL,
    job TEXT NOT NULL DEFAULT ''
);
CREATE INDEX IF NOT EXISTS idx_meas_workload
    ON strategy_measurements (workload, created_at);
CREATE TABLE IF NOT EXISTS speed_samples (
    job TEXT NOT NULL,
    worker_count INTEGER NOT NULL,
    records_per_sec REAL NOT NULL,
    created_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_speed_job
    ON speed_samples (job, worker_count, created_at);
CREATE TABLE IF NOT EXISTS node_events (
    job TEXT NOT NULL,
    node TEXT NOT NULL,
    event_type TEXT NOT NULL,
    detail TEXT NOT NULL DEFAULT '',
    created_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_events_job
    ON node_events (job, created_at);
CREATE TABLE IF NOT EXISTS timeline_events (
    job TEXT NOT NULL,
    node INTEGER NOT NULL DEFAULT 0,
    rank INTEGER NOT NULL DEFAULT -1,
    inc INTEGER NOT NULL DEFAULT 0,
    name TEXT NOT NULL,
    ph TEXT NOT NULL,
    wall REAL NOT NULL,
    mono REAL NOT NULL DEFAULT 0,
    dur REAL,
    sid INTEGER,
    pid INTEGER,
    labels TEXT NOT NULL DEFAULT '{}',
    created_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_timeline_job
    ON timeline_events (job, wall);
CREATE TABLE IF NOT EXISTS profiles (
    job TEXT NOT NULL,
    node INTEGER NOT NULL,
    kind TEXT NOT NULL DEFAULT 'capture',
    reason TEXT NOT NULL DEFAULT '',
    summary TEXT NOT NULL DEFAULT '{}',
    artifact TEXT NOT NULL DEFAULT '',
    created_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_profiles_job
    ON profiles (job, created_at);
CREATE TABLE IF NOT EXISTS control_journal (
    job TEXT NOT NULL,
    seq INTEGER NOT NULL,
    component TEXT NOT NULL,
    op TEXT NOT NULL,
    args TEXT NOT NULL DEFAULT '{}',
    created_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_journal_job
    ON control_journal (job, seq);
CREATE TABLE IF NOT EXISTS control_snapshots (
    job TEXT NOT NULL,
    seq INTEGER NOT NULL,
    state TEXT NOT NULL,
    created_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_snapshot_job
    ON control_snapshots (job, seq);
CREATE TABLE IF NOT EXISTS control_meta (
    job TEXT PRIMARY KEY,
    job_epoch INTEGER NOT NULL DEFAULT 1,
    incarnation INTEGER NOT NULL DEFAULT 0,
    updated_at REAL NOT NULL
);
"""


def workload_signature(key: Tuple) -> str:
    """Stable string form of a workload-identity tuple (the strategy
    service's ``_workload_key``)."""
    return json.dumps(list(key), separators=(",", ":"))


_SQL_MEASUREMENT = (
    "INSERT INTO strategy_measurements "
    "(workload, strategy, step_time_s, created_at, job) "
    "VALUES (?,?,?,?,?)"
)
_SQL_SPEED = "INSERT INTO speed_samples VALUES (?,?,?,?)"
_SQL_NODE_EVENT = "INSERT INTO node_events VALUES (?,?,?,?,?)"
_SQL_TIMELINE = (
    "INSERT INTO timeline_events VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?)"
)
_SQL_JOURNAL = "INSERT INTO control_journal VALUES (?,?,?,?,?,?)"
_SQL_PROFILE = "INSERT INTO profiles VALUES (?,?,?,?,?,?,?)"


class BrainDatastore:
    """Embedded durable store for the master's learned state."""

    #: write-behind linger: a burst accumulates this long before the
    #: flusher commits it as one batch
    FLUSH_AGE_S = 0.2
    #: bounded queue: recorders block (briefly) past this many pending
    #: rows instead of growing memory without bound
    MAX_PENDING = 10_000

    def __init__(self, db_path: str, sync: Optional[bool] = None):
        self.path = db_path
        self._sync = (
            datastore_sync_enabled() if sync is None else bool(sync)
        )
        # write-behind state (all guarded by _wb_cond; _enqueued /
        # _flushed count ROWS so a drain barrier is a counter compare)
        self._wb_cond = threading.Condition()
        self._pending: List[Tuple[str, tuple]] = []
        self._enqueued = 0
        self._flushed = 0
        self._drain_waiters = 0
        self._closed = False
        #: per-job monotonic journal sequence, initialized lazily from
        #: MAX(seq) so a restarted master keeps appending after the
        #: rows its predecessor landed
        self._journal_seq: Dict[str, int] = {}
        self._journal_seq_lock = threading.Lock()
        self._flusher: Optional[threading.Thread] = None
        parent = os.path.dirname(os.path.abspath(db_path))
        os.makedirs(parent, exist_ok=True)
        self._lock = threading.Lock()
        # timeout + WAL: the store is no longer single-master — a
        # fleet can point several job masters at one db file (the
        # reference's cluster-wide Brain over MySQL,
        # ref: dlrover/go/brain/pkg/datastore/dbbase/recorder.go:280)
        # and WAL lets one master read while another commits
        self._conn = sqlite3.connect(
            db_path, check_same_thread=False, timeout=10.0
        )
        with self._lock:
            try:
                self._conn.execute("PRAGMA journal_mode=WAL")
                self._conn.execute("PRAGMA busy_timeout=10000")
            except sqlite3.OperationalError:
                pass  # read-only FS etc.: plain journaling still works
            self._conn.executescript(_SCHEMA)
            # migration: pre-r5 files lack the job column on
            # strategy_measurements (calibration provenance +
            # per-job pruning)
            try:
                self._conn.execute(
                    "ALTER TABLE strategy_measurements "
                    "ADD COLUMN job TEXT NOT NULL DEFAULT ''"
                )
            except sqlite3.OperationalError:
                pass  # column already present
            self._conn.commit()
        logger.info("brain datastore at %s", db_path)
        # startup hygiene: long-lived masters append forever, and the
        # reads are LIMITed but full-table scans (measured_workloads)
        # and the file itself keep growing — drop ancient rows here so
        # every restart bounds the store (ADVICE-r4).  The FIXED 30d
        # floor applies globally; the operator's env override applies
        # only to THIS job's rows when a job name is known — in a
        # shared multi-job db, one short-retention job restarting must
        # not delete its neighbours' history
        self.prune(30.0 * 24 * 3600)
        env_age = os.getenv("DLROVER_TPU_BRAIN_MAX_AGE_S")
        if env_age:
            try:
                age = float(env_age)
            except ValueError:
                logger.warning(
                    "ignoring malformed DLROVER_TPU_BRAIN_MAX_AGE_S"
                    "=%r", env_age,
                )
            else:
                own_job = os.getenv("DLROVER_TPU_JOB_NAME", "")
                if own_job:
                    self.prune(age, job=own_job)
                else:
                    # no job identity: a job=None prune would be
                    # GLOBAL and delete every other job's rows from a
                    # shared db (ADVICE-r5) — refuse, keep the fixed
                    # 30d floor above as the only global hygiene
                    logger.warning(
                        "DLROVER_TPU_BRAIN_MAX_AGE_S=%s set but "
                        "DLROVER_TPU_JOB_NAME is empty; skipping the "
                        "job-scoped startup prune (a global prune "
                        "would delete other jobs' history)",
                        env_age,
                    )
        if not self._sync:
            self._flusher = threading.Thread(
                target=self._flusher_loop,
                name="brain-write-behind",
                daemon=True,
            )
            self._flusher.start()

    # ----------------------------------------------- write-behind core
    def _submit(self, sql: str, rows: List[tuple]):
        """Record rows: synchronous INSERT+commit under
        ``DLROVER_TPU_DATASTORE_SYNC=1`` (the pre-write-behind
        behavior), else enqueue for the background flusher."""
        if not rows:
            return
        if self._sync:
            with self._lock:
                self._conn.executemany(sql, rows)
                self._conn.commit()
            return
        with self._wb_cond:

            def _flusher_alive():
                return (
                    self._flusher is not None
                    and self._flusher.is_alive()
                )

            # bounded queue: backpressure instead of unbounded memory;
            # the flusher drains fast enough that this only trips on a
            # pathological burst
            while (
                len(self._pending) >= self.MAX_PENDING
                and not self._closed
                and _flusher_alive()
            ):
                self._wb_cond.wait(0.05)
            if not self._closed and _flusher_alive():
                self._pending.extend((sql, row) for row in rows)
                self._enqueued += len(rows)
                self._wb_cond.notify_all()
                return
        # post-close (or dead-flusher) writes fall back to
        # synchronous-direct so nothing silently vanishes
        with self._lock:
            self._conn.executemany(sql, rows)
            self._conn.commit()

    def _flusher_loop(self):
        while True:
            with self._wb_cond:
                while not self._pending and not self._closed:
                    self._wb_cond.wait()
                if not self._pending and self._closed:
                    return
                # linger so a burst coalesces into one commit — unless
                # we're closing or a reader is parked on a drain
                if not self._closed and not self._drain_waiters:
                    self._wb_cond.wait(self.FLUSH_AGE_S)
                batch, self._pending = self._pending, []
                self._wb_cond.notify_all()  # wake backpressure waiters
            self._write_batch(batch)
            with self._wb_cond:
                self._flushed += len(batch)
                self._wb_cond.notify_all()  # wake drain waiters

    def _write_batch(self, batch: List[Tuple[str, tuple]]):
        """Per-table ``executemany`` over consecutive same-SQL runs
        (insertion order preserved), ONE commit for the whole batch.
        Commit latency lands in the
        ``dlrover_tpu_datastore_flush_seconds`` histogram (self-obs)
        — its tail IS the durability lag of everything the journal
        claims committed."""
        # chaos hook: the enqueue->flush window is exactly where a
        # crash tears the write-behind tail; the fault plan can pin a
        # SIGKILL here to prove journal replay tolerates it
        from dlrover_tpu.common.fault_injection import maybe_crash
        from dlrover_tpu.observability.metrics import (
            record_datastore_flush,
        )

        maybe_crash("mid_report_flush")
        t0 = time.perf_counter()
        self._flush_batch_locked(batch)
        record_datastore_flush(
            len(batch), time.perf_counter() - t0
        )

    def _flush_batch_locked(self, batch: List[Tuple[str, tuple]]):
        with self._lock:
            try:
                i = 0
                while i < len(batch):
                    sql = batch[i][0]
                    j = i
                    while j < len(batch) and batch[j][0] == sql:
                        j += 1
                    self._conn.executemany(
                        sql, [row for _, row in batch[i:j]]
                    )
                    i = j
                self._conn.commit()
            except sqlite3.Error as e:
                logger.warning(
                    "write-behind flush dropped %d rows: %s",
                    len(batch), e,
                )
                try:
                    self._conn.rollback()
                except sqlite3.Error:
                    pass

    def health(self) -> dict:
        """The write-behind queue's live vitals for the master's
        self-telemetry: queue depth vs bound (backpressure distance)
        and the JOURNAL LAG — rows enqueued minus rows flushed, i.e.
        how much claimed-durable state a crash right now would lose.
        Cheap (one lock hold, no sqlite); safe to call per scrape."""
        with self._wb_cond:
            return {
                "sync": self._sync,
                "queue_depth": len(self._pending),
                "queue_cap": self.MAX_PENDING,
                "enqueued_rows": self._enqueued,
                "flushed_rows": self._flushed,
                "lag_rows": max(self._enqueued - self._flushed, 0),
                "flusher_alive": bool(
                    self._flusher is not None
                    and self._flusher.is_alive()
                ),
            }

    def _drain(self):
        """Barrier: block until every row enqueued so far is
        committed — readers call this first, preserving exact
        read-your-writes semantics over the async queue."""
        if self._sync:
            return
        if threading.current_thread() is self._flusher:
            return  # the flusher itself must never self-deadlock
        with self._wb_cond:
            target = self._enqueued
            self._drain_waiters += 1
            self._wb_cond.notify_all()  # cut the flusher's linger short
            try:
                while self._flushed < target:
                    if (
                        self._flusher is None
                        or not self._flusher.is_alive()
                    ):
                        break  # dead flusher must not hang readers
                    self._wb_cond.wait(0.05)
            finally:
                self._drain_waiters -= 1

    # ------------------------------------------- strategy measurements
    def record_measurement(
        self,
        workload: str,
        strategy: Dict,
        step_time_s: float,
        job: str = "",
    ):
        """``job`` tags provenance: measurements are keyed by
        WORKLOAD (hardware+model signature), so any job's master can
        learn from any other job's calibration through a shared db
        file — the cluster-wide role of the reference's Brain.

        Deliberately SYNCHRONOUS even in write-behind mode: a
        concurrently-live neighbour master reads this file directly,
        so a measurement must be committed (not parked in this
        process's queue) the moment the recorder returns — and the
        rate is one row per calibration step, not a hot path."""
        row = (
            workload,
            json.dumps(strategy, separators=(",", ":")),
            float(step_time_s),
            time.time(),
            job,
        )
        with self._lock:
            self._conn.execute(_SQL_MEASUREMENT, row)
            self._conn.commit()

    def load_measurements(
        self, workload: str, limit: int = 64
    ) -> List[Tuple[Dict, float]]:
        """Newest ``limit`` measurements for a workload, oldest
        first (matches the in-memory history ordering)."""
        self._drain()
        with self._lock:
            rows = self._conn.execute(
                "SELECT strategy, step_time_s FROM ("
                "  SELECT strategy, step_time_s, created_at"
                "  FROM strategy_measurements WHERE workload = ?"
                "  ORDER BY created_at DESC LIMIT ?"
                ") ORDER BY created_at ASC",
                (workload, limit),
            ).fetchall()
        out = []
        for strategy_json, step_time in rows:
            try:
                out.append((json.loads(strategy_json), step_time))
            except json.JSONDecodeError:
                continue
        return out

    def measured_workloads(self) -> List[str]:
        self._drain()
        with self._lock:
            rows = self._conn.execute(
                "SELECT DISTINCT workload FROM strategy_measurements"
            ).fetchall()
        return [r[0] for r in rows]

    # ------------------------------------------------- speed samples
    def record_speed(
        self, job: str, worker_count: int, records_per_sec: float
    ):
        self._submit(
            _SQL_SPEED,
            [
                (
                    job,
                    int(worker_count),
                    float(records_per_sec),
                    time.time(),
                )
            ],
        )

    def speed_history(
        self, job: str, max_age_s: Optional[float] = None
    ) -> Dict[int, float]:
        """Best observed speed per worker count (what WorkerResource's
        marginal-gain model consumes)."""
        q = (
            "SELECT worker_count, MAX(records_per_sec) "
            "FROM speed_samples WHERE job = ?"
        )
        args: List = [job]
        if max_age_s is not None:
            q += " AND created_at >= ?"
            args.append(time.time() - max_age_s)
        q += " GROUP BY worker_count"
        self._drain()
        with self._lock:
            rows = self._conn.execute(q, args).fetchall()
        return {int(n): float(v) for n, v in rows}

    # --------------------------------------------------- node events
    def record_node_event(
        self, job: str, node: str, event_type: str, detail: str = ""
    ):
        self._submit(
            _SQL_NODE_EVENT,
            [(job, str(node), event_type, detail, time.time())],
        )

    def node_events(
        self, job: str, limit: int = 100
    ) -> List[Dict]:
        self._drain()
        with self._lock:
            rows = self._conn.execute(
                "SELECT node, event_type, detail, created_at "
                "FROM node_events WHERE job = ? "
                "ORDER BY created_at DESC LIMIT ?",
                (job, limit),
            ).fetchall()
        return [
            {
                "node": n,
                "event_type": e,
                "detail": d,
                "created_at": t,
            }
            for n, e, d, t in rows
        ]

    # -------------------------------------------------- deep captures
    def record_profile(
        self,
        job: str,
        node: int,
        kind: str = "capture",
        reason: str = "",
        summary: Optional[Dict] = None,
        artifact: str = "",
    ):
        """One deep-capture (or profile) row: the diagnosis-triggered
        capture evidence survives master failover like the rest of
        the Brain."""
        self._submit(
            _SQL_PROFILE,
            [
                (
                    job,
                    int(node),
                    str(kind),
                    str(reason),
                    json.dumps(
                        summary or {},
                        separators=(",", ":"),
                        default=str,
                    ),
                    str(artifact),
                    time.time(),
                )
            ],
        )

    def profiles(self, job: str, limit: int = 32) -> List[Dict]:
        """Newest ``limit`` capture rows for a job, newest first."""
        self._drain()
        with self._lock:
            rows = self._conn.execute(
                "SELECT node, kind, reason, summary, artifact, "
                "created_at FROM profiles WHERE job = ? "
                "ORDER BY created_at DESC LIMIT ?",
                (job, limit),
            ).fetchall()
        out = []
        for node, kind, reason, summary, artifact, created_at in rows:
            try:
                parsed = json.loads(summary) if summary else {}
            except json.JSONDecodeError:
                parsed = {}
            out.append(
                {
                    "node": node,
                    "kind": kind,
                    "reason": reason,
                    "summary": parsed,
                    "artifact": artifact,
                    "created_at": created_at,
                }
            )
        return out

    # ---------------------------------------------- timeline events
    def record_timeline_events(self, job: str, events: List[Dict]):
        """Persist a batch of timeline records (the JSONL schema of
        ``observability/events.py``) — the master's merged job-event
        timeline survives master restarts like the rest of the Brain."""
        now = time.time()
        rows = []
        for e in events:
            if not isinstance(e, dict) or "name" not in e:
                continue
            rows.append(
                (
                    job,
                    int(e.get("node", 0) or 0),
                    int(e.get("rank", -1) if e.get("rank")
                        is not None else -1),
                    int(e.get("inc", 0) or 0),
                    str(e.get("name", "")),
                    str(e.get("ph", "i")),
                    float(e.get("wall", now) or now),
                    float(e.get("mono", 0.0) or 0.0),
                    float(e["dur"]) if e.get("dur") is not None
                    else None,
                    int(e["sid"]) if e.get("sid") is not None
                    else None,
                    int(e.get("pid", 0) or 0),
                    json.dumps(
                        e.get("labels") or {}, separators=(",", ":")
                    ),
                    now,
                )
            )
        # write-behind: a node's whole timeline batch is one enqueue;
        # the background flusher lands it (plus whatever else is
        # pending) with one executemany + one commit — the report RPC
        # path no longer pays sqlite latency
        self._submit(_SQL_TIMELINE, rows)

    def timeline_events(
        self, job: str, limit: int = 10000
    ) -> List[Dict]:
        """Newest ``limit`` timeline records, oldest first (ready for
        ``compute_ledger`` / ``export_chrome_trace``)."""
        self._drain()
        with self._lock:
            rows = self._conn.execute(
                "SELECT node, rank, inc, name, ph, wall, mono, dur, "
                "sid, pid, labels FROM ("
                "  SELECT * FROM timeline_events WHERE job = ?"
                "  ORDER BY wall DESC LIMIT ?"
                ") ORDER BY wall ASC",
                (job, limit),
            ).fetchall()
        out = []
        for (node, rank, inc, name, ph, wall, mono, dur, sid, pid,
             labels) in rows:
            rec = {
                "name": name,
                "ph": ph,
                "wall": wall,
                "mono": mono,
                "job": job,
                "node": node,
                "rank": rank,
                "inc": inc,
                "pid": pid,
            }
            if dur is not None:
                rec["dur"] = dur
            if sid is not None:
                rec["sid"] = sid
            try:
                parsed = json.loads(labels) if labels else {}
            except json.JSONDecodeError:
                parsed = {}
            if parsed:
                rec["labels"] = parsed
            out.append(rec)
        return out

    # --------------------------------------- control-plane durability
    def _next_journal_seq(self, job: str) -> int:
        with self._journal_seq_lock:
            if job not in self._journal_seq:
                with self._lock:
                    row = self._conn.execute(
                        "SELECT MAX(seq) FROM control_journal "
                        "WHERE job = ?",
                        (job,),
                    ).fetchone()
                self._journal_seq[job] = int(row[0] or 0)
            self._journal_seq[job] += 1
            return self._journal_seq[job]

    def journal_append(
        self, job: str, component: str, op: str, args: Dict
    ) -> int:
        """Append one control-plane mutation record (write-behind: the
        report RPC path that triggered it never blocks on sqlite).
        Returns the assigned sequence number."""
        seq = self._next_journal_seq(job)
        self._submit(
            _SQL_JOURNAL,
            [(
                job,
                seq,
                component,
                op,
                json.dumps(args, separators=(",", ":"), default=str),
                time.time(),
            )],
        )
        return seq

    def journal_seq(self, job: str) -> int:
        """Highest sequence number HANDED OUT so far (enqueued, not
        necessarily flushed) — the snapshot low-water mark."""
        with self._journal_seq_lock:
            if job in self._journal_seq:
                return self._journal_seq[job]
        self._drain()
        with self._lock:
            row = self._conn.execute(
                "SELECT MAX(seq) FROM control_journal WHERE job = ?",
                (job,),
            ).fetchone()
        return int(row[0] or 0)

    def journal_entries(
        self, job: str, since_seq: int = 0
    ) -> List[Tuple[int, str, str, Dict]]:
        """Journal records with ``seq > since_seq``, oldest first, as
        ``(seq, component, op, args)``.

        Torn-tail tolerance: a crash can leave the NEWEST record's
        ``args`` column unparseable; recovery truncates to the last
        complete record (everything after the first bad row is
        dropped with a warning) and NEVER raises — the dropped tail
        is at most the linger window of un-fsynced mutations, exactly
        what a crash loses anyway."""
        self._drain()
        with self._lock:
            rows = self._conn.execute(
                "SELECT seq, component, op, args FROM control_journal "
                "WHERE job = ? AND seq > ? ORDER BY seq ASC",
                (job, since_seq),
            ).fetchall()
        out: List[Tuple[int, str, str, Dict]] = []
        for seq, component, op, args in rows:
            try:
                parsed = json.loads(args) if args else {}
            except (json.JSONDecodeError, TypeError) as e:
                logger.warning(
                    "journal replay for %s truncated at seq %s "
                    "(torn tail: %s); %d records replayed",
                    job, seq, e, len(out),
                )
                break
            out.append((int(seq), component, op, parsed))
        return out

    def save_control_snapshot(self, job: str, state: Dict, seq: int):
        """Persist a compacted snapshot of the whole control-plane
        state at journal position ``seq`` and prune journal records it
        subsumes.  Synchronous (rare — one row per snapshot interval);
        replay = snapshot + entries with ``seq > snapshot.seq``."""
        payload = json.dumps(state, separators=(",", ":"), default=str)
        # flush pending write-behind journal rows first: a row with
        # seq <= snapshot.seq landing AFTER the prune would linger in
        # the table forever (harmless for replay — since_seq filters
        # it — but it defeats the compaction)
        self._drain()
        with self._lock:
            self._conn.execute(
                "DELETE FROM control_snapshots WHERE job = ?", (job,)
            )
            self._conn.execute(
                "INSERT INTO control_snapshots VALUES (?,?,?,?)",
                (job, int(seq), payload, time.time()),
            )
            self._conn.execute(
                "DELETE FROM control_journal "
                "WHERE job = ? AND seq <= ?",
                (job, int(seq)),
            )
            self._conn.commit()

    def load_control_snapshot(
        self, job: str
    ) -> Tuple[Optional[Dict], int]:
        """Newest snapshot for ``job`` as ``(state, seq)``; ``(None,
        0)`` when absent or unparseable (a torn snapshot falls back to
        journal-only replay)."""
        self._drain()
        with self._lock:
            row = self._conn.execute(
                "SELECT state, seq FROM control_snapshots "
                "WHERE job = ? ORDER BY seq DESC LIMIT 1",
                (job,),
            ).fetchone()
        if row is None:
            return None, 0
        try:
            return json.loads(row[0]), int(row[1])
        except (json.JSONDecodeError, TypeError) as e:
            logger.warning(
                "control snapshot for %s unreadable (%s); replaying "
                "journal from scratch", job, e,
            )
            return None, 0

    def bump_incarnation(self, job: str) -> Tuple[int, int]:
        """Register a master start: increments the incarnation, keeps
        the job epoch (a restarted master serves the SAME job).
        Returns ``(job_epoch, incarnation)``.  Synchronous — the pair
        fences every subsequent RPC, so it must be durable before the
        server opens."""
        with self._lock:
            now = time.time()
            self._conn.execute(
                "INSERT INTO control_meta VALUES (?, 1, 1, ?) "
                "ON CONFLICT(job) DO UPDATE SET "
                "incarnation = incarnation + 1, updated_at = ?",
                (job, now, now),
            )
            self._conn.commit()
            row = self._conn.execute(
                "SELECT job_epoch, incarnation FROM control_meta "
                "WHERE job = ?",
                (job,),
            ).fetchone()
        return int(row[0]), int(row[1])

    def bump_job_epoch(self, job: str) -> int:
        """Declare a NEW job generation on this master address: bumps
        the epoch so clients of the previous generation are fenced
        into a refresh, and drops the old generation's journal,
        snapshot and per-job epoch-scoped state."""
        # enqueued rows of the dying generation must not outlive it
        self._drain()
        with self._lock:
            now = time.time()
            self._conn.execute(
                "INSERT INTO control_meta VALUES (?, 1, 0, ?) "
                "ON CONFLICT(job) DO UPDATE SET "
                "job_epoch = job_epoch + 1, incarnation = 0, "
                "updated_at = ?",
                (job, now, now),
            )
            self._conn.execute(
                "DELETE FROM control_journal WHERE job = ?", (job,)
            )
            self._conn.execute(
                "DELETE FROM control_snapshots WHERE job = ?", (job,)
            )
            self._conn.commit()
            row = self._conn.execute(
                "SELECT job_epoch FROM control_meta WHERE job = ?",
                (job,),
            ).fetchone()
        with self._journal_seq_lock:
            self._journal_seq.pop(job, None)
        return int(row[0])

    def get_control_meta(self, job: str) -> Tuple[int, int]:
        """Current ``(job_epoch, incarnation)`` without bumping
        (``(1, 0)`` when the job was never registered)."""
        with self._lock:
            row = self._conn.execute(
                "SELECT job_epoch, incarnation FROM control_meta "
                "WHERE job = ?",
                (job,),
            ).fetchone()
        if row is None:
            return 1, 0
        return int(row[0]), int(row[1])

    def sweep_timeline(
        self,
        job: str,
        max_age_s: Optional[float] = None,
        max_rows: Optional[int] = None,
    ):
        """Retention sweep for ONE job's ``timeline_events`` rows:
        drop rows older than ``max_age_s`` AND cap the job to the
        newest ``max_rows`` (0 disables either bound).  Defaults come
        from ``DLROVER_TPU_TIMELINE_MAX_AGE_S`` /
        ``DLROVER_TPU_TIMELINE_MAX_ROWS`` (generous: 7 days / 500k
        rows).  Job-scoped on purpose — a shared multi-job Brain must
        never lose a neighbour's history to this job's sweep."""
        from dlrover_tpu.common.env import (
            timeline_max_age_s,
            timeline_max_rows,
        )

        if max_age_s is None:
            max_age_s = timeline_max_age_s()
        if max_rows is None:
            max_rows = timeline_max_rows()
        self._drain()
        with self._lock:
            if max_age_s and max_age_s > 0:
                self._conn.execute(
                    "DELETE FROM timeline_events "
                    "WHERE job = ? AND created_at < ?",
                    (job, time.time() - max_age_s),
                )
            if max_rows and max_rows > 0:
                # newest rows win: delete everything below the
                # max_rows-th newest (created_at, wall) position
                self._conn.execute(
                    "DELETE FROM timeline_events WHERE job = ? "
                    "AND rowid NOT IN ("
                    "  SELECT rowid FROM timeline_events "
                    "  WHERE job = ? "
                    "  ORDER BY created_at DESC, wall DESC LIMIT ?"
                    ")",
                    (job, job, int(max_rows)),
                )
            self._conn.commit()

    # ------------------------------------------------------- hygiene
    def prune(self, max_age_s: float, job: Optional[str] = None):
        """Drop rows older than ``max_age_s``; with ``job`` given,
        only that job's rows (a finished job's master cleans up after
        itself without touching its neighbours' history in a shared
        db)."""
        cutoff = time.time() - max_age_s
        self._drain()
        with self._lock:
            for table in (
                "strategy_measurements",
                "speed_samples",
                "node_events",
                "timeline_events",
                "profiles",
            ):
                q = f"DELETE FROM {table} WHERE created_at < ?"  # noqa: S608 - fixed table names
                args: List = [cutoff]
                if job is not None:
                    q += " AND job = ?"
                    args.append(job)
                self._conn.execute(q, args)
            self._conn.commit()

    def close(self):
        """Drain the write-behind queue (zero rows lost — pinned by
        tests), checkpoint the WAL so the bytes are fsync'd into the
        main db file, then close."""
        if not self._sync:
            with self._wb_cond:
                self._closed = True
                self._wb_cond.notify_all()
            if self._flusher is not None:
                self._flusher.join(timeout=10.0)
        with self._lock:
            try:
                self._conn.commit()
                self._conn.execute("PRAGMA wal_checkpoint(TRUNCATE)")
            except sqlite3.Error:
                pass  # non-WAL / read-only FS: commit already landed
            self._conn.close()


_default_store: Optional[BrainDatastore] = None


def get_default_datastore() -> Optional[BrainDatastore]:
    """Process-wide datastore, enabled by ``DLROVER_TPU_BRAIN_DB``
    (the master sets it; absent = history stays in-memory only)."""
    global _default_store
    if _default_store is None:
        path = os.getenv("DLROVER_TPU_BRAIN_DB", "")
        if path:
            _default_store = BrainDatastore(path)
    return _default_store
