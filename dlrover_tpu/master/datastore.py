"""Durable Brain datastore: cross-restart job/fleet history (sqlite).

Reference parity: the Go Brain persists job metrics to MySQL so
optimization learns across restarts and across jobs
(``dlrover/go/brain/pkg/datastore/``, ``dbbase/recorder.go:280``,
``docs/design/db-design.md``).  The TPU redesign trades the external
DB for an embedded sqlite file: a single-master control plane needs
durability and queryability, not a fleet-shared SQL server — and a
file on the master's persistent volume survives master restarts, which
is the failure mode that matters (VERDICT-r3: "a master restart loses
everything learned").

Three recorders:
- strategy measurements  (workload signature -> (strategy, step time))
  — feeds the strategy service's CalibratedPlanner across restarts
- speed samples          (worker count -> records/sec per job)
  — feeds WorkerResource's marginal-gain decisions
- node events            (failures, OOMs, relaunches per job)
  — the diagnosis/audit trail

All writes are synchronous and tiny (control-plane rates); one lock
serializes the shared connection (sqlite's own locking is per-process
anyway).
"""

import json
import os
import sqlite3
import threading
import time
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.common.log import default_logger as logger

_SCHEMA = """
CREATE TABLE IF NOT EXISTS strategy_measurements (
    workload TEXT NOT NULL,
    strategy TEXT NOT NULL,
    step_time_s REAL NOT NULL,
    created_at REAL NOT NULL,
    job TEXT NOT NULL DEFAULT ''
);
CREATE INDEX IF NOT EXISTS idx_meas_workload
    ON strategy_measurements (workload, created_at);
CREATE TABLE IF NOT EXISTS speed_samples (
    job TEXT NOT NULL,
    worker_count INTEGER NOT NULL,
    records_per_sec REAL NOT NULL,
    created_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_speed_job
    ON speed_samples (job, worker_count, created_at);
CREATE TABLE IF NOT EXISTS node_events (
    job TEXT NOT NULL,
    node TEXT NOT NULL,
    event_type TEXT NOT NULL,
    detail TEXT NOT NULL DEFAULT '',
    created_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_events_job
    ON node_events (job, created_at);
CREATE TABLE IF NOT EXISTS timeline_events (
    job TEXT NOT NULL,
    node INTEGER NOT NULL DEFAULT 0,
    rank INTEGER NOT NULL DEFAULT -1,
    inc INTEGER NOT NULL DEFAULT 0,
    name TEXT NOT NULL,
    ph TEXT NOT NULL,
    wall REAL NOT NULL,
    mono REAL NOT NULL DEFAULT 0,
    dur REAL,
    sid INTEGER,
    pid INTEGER,
    labels TEXT NOT NULL DEFAULT '{}',
    created_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_timeline_job
    ON timeline_events (job, wall);
"""


def workload_signature(key: Tuple) -> str:
    """Stable string form of a workload-identity tuple (the strategy
    service's ``_workload_key``)."""
    return json.dumps(list(key), separators=(",", ":"))


class BrainDatastore:
    """Embedded durable store for the master's learned state."""

    def __init__(self, db_path: str):
        self.path = db_path
        parent = os.path.dirname(os.path.abspath(db_path))
        os.makedirs(parent, exist_ok=True)
        self._lock = threading.Lock()
        # timeout + WAL: the store is no longer single-master — a
        # fleet can point several job masters at one db file (the
        # reference's cluster-wide Brain over MySQL,
        # ref: dlrover/go/brain/pkg/datastore/dbbase/recorder.go:280)
        # and WAL lets one master read while another commits
        self._conn = sqlite3.connect(
            db_path, check_same_thread=False, timeout=10.0
        )
        with self._lock:
            try:
                self._conn.execute("PRAGMA journal_mode=WAL")
                self._conn.execute("PRAGMA busy_timeout=10000")
            except sqlite3.OperationalError:
                pass  # read-only FS etc.: plain journaling still works
            self._conn.executescript(_SCHEMA)
            # migration: pre-r5 files lack the job column on
            # strategy_measurements (calibration provenance +
            # per-job pruning)
            try:
                self._conn.execute(
                    "ALTER TABLE strategy_measurements "
                    "ADD COLUMN job TEXT NOT NULL DEFAULT ''"
                )
            except sqlite3.OperationalError:
                pass  # column already present
            self._conn.commit()
        logger.info("brain datastore at %s", db_path)
        # startup hygiene: long-lived masters append forever, and the
        # reads are LIMITed but full-table scans (measured_workloads)
        # and the file itself keep growing — drop ancient rows here so
        # every restart bounds the store (ADVICE-r4).  The FIXED 30d
        # floor applies globally; the operator's env override applies
        # only to THIS job's rows when a job name is known — in a
        # shared multi-job db, one short-retention job restarting must
        # not delete its neighbours' history
        self.prune(30.0 * 24 * 3600)
        env_age = os.getenv("DLROVER_TPU_BRAIN_MAX_AGE_S")
        if env_age:
            try:
                age = float(env_age)
            except ValueError:
                logger.warning(
                    "ignoring malformed DLROVER_TPU_BRAIN_MAX_AGE_S"
                    "=%r", env_age,
                )
            else:
                own_job = os.getenv("DLROVER_TPU_JOB_NAME", "")
                if own_job:
                    self.prune(age, job=own_job)
                else:
                    # no job identity: a job=None prune would be
                    # GLOBAL and delete every other job's rows from a
                    # shared db (ADVICE-r5) — refuse, keep the fixed
                    # 30d floor above as the only global hygiene
                    logger.warning(
                        "DLROVER_TPU_BRAIN_MAX_AGE_S=%s set but "
                        "DLROVER_TPU_JOB_NAME is empty; skipping the "
                        "job-scoped startup prune (a global prune "
                        "would delete other jobs' history)",
                        env_age,
                    )

    # ------------------------------------------- strategy measurements
    def record_measurement(
        self,
        workload: str,
        strategy: Dict,
        step_time_s: float,
        job: str = "",
    ):
        """``job`` tags provenance: measurements are keyed by
        WORKLOAD (hardware+model signature), so any job's master can
        learn from any other job's calibration through a shared db
        file — the cluster-wide role of the reference's Brain."""
        with self._lock:
            self._conn.execute(
                "INSERT INTO strategy_measurements "
                "(workload, strategy, step_time_s, created_at, job) "
                "VALUES (?,?,?,?,?)",
                (
                    workload,
                    json.dumps(strategy, separators=(",", ":")),
                    float(step_time_s),
                    time.time(),
                    job,
                ),
            )
            self._conn.commit()

    def load_measurements(
        self, workload: str, limit: int = 64
    ) -> List[Tuple[Dict, float]]:
        """Newest ``limit`` measurements for a workload, oldest
        first (matches the in-memory history ordering)."""
        with self._lock:
            rows = self._conn.execute(
                "SELECT strategy, step_time_s FROM ("
                "  SELECT strategy, step_time_s, created_at"
                "  FROM strategy_measurements WHERE workload = ?"
                "  ORDER BY created_at DESC LIMIT ?"
                ") ORDER BY created_at ASC",
                (workload, limit),
            ).fetchall()
        out = []
        for strategy_json, step_time in rows:
            try:
                out.append((json.loads(strategy_json), step_time))
            except json.JSONDecodeError:
                continue
        return out

    def measured_workloads(self) -> List[str]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT DISTINCT workload FROM strategy_measurements"
            ).fetchall()
        return [r[0] for r in rows]

    # ------------------------------------------------- speed samples
    def record_speed(
        self, job: str, worker_count: int, records_per_sec: float
    ):
        with self._lock:
            self._conn.execute(
                "INSERT INTO speed_samples VALUES (?,?,?,?)",
                (
                    job,
                    int(worker_count),
                    float(records_per_sec),
                    time.time(),
                ),
            )
            self._conn.commit()

    def speed_history(
        self, job: str, max_age_s: Optional[float] = None
    ) -> Dict[int, float]:
        """Best observed speed per worker count (what WorkerResource's
        marginal-gain model consumes)."""
        q = (
            "SELECT worker_count, MAX(records_per_sec) "
            "FROM speed_samples WHERE job = ?"
        )
        args: List = [job]
        if max_age_s is not None:
            q += " AND created_at >= ?"
            args.append(time.time() - max_age_s)
        q += " GROUP BY worker_count"
        with self._lock:
            rows = self._conn.execute(q, args).fetchall()
        return {int(n): float(v) for n, v in rows}

    # --------------------------------------------------- node events
    def record_node_event(
        self, job: str, node: str, event_type: str, detail: str = ""
    ):
        with self._lock:
            self._conn.execute(
                "INSERT INTO node_events VALUES (?,?,?,?,?)",
                (job, str(node), event_type, detail, time.time()),
            )
            self._conn.commit()

    def node_events(
        self, job: str, limit: int = 100
    ) -> List[Dict]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT node, event_type, detail, created_at "
                "FROM node_events WHERE job = ? "
                "ORDER BY created_at DESC LIMIT ?",
                (job, limit),
            ).fetchall()
        return [
            {
                "node": n,
                "event_type": e,
                "detail": d,
                "created_at": t,
            }
            for n, e, d, t in rows
        ]

    # ---------------------------------------------- timeline events
    def record_timeline_events(self, job: str, events: List[Dict]):
        """Persist a batch of timeline records (the JSONL schema of
        ``observability/events.py``) — the master's merged job-event
        timeline survives master restarts like the rest of the Brain."""
        now = time.time()
        rows = []
        for e in events:
            if not isinstance(e, dict) or "name" not in e:
                continue
            rows.append(
                (
                    job,
                    int(e.get("node", 0) or 0),
                    int(e.get("rank", -1) if e.get("rank")
                        is not None else -1),
                    int(e.get("inc", 0) or 0),
                    str(e.get("name", "")),
                    str(e.get("ph", "i")),
                    float(e.get("wall", now) or now),
                    float(e.get("mono", 0.0) or 0.0),
                    float(e["dur"]) if e.get("dur") is not None
                    else None,
                    int(e["sid"]) if e.get("sid") is not None
                    else None,
                    int(e.get("pid", 0) or 0),
                    json.dumps(
                        e.get("labels") or {}, separators=(",", ":")
                    ),
                    now,
                )
            )
        if not rows:
            return
        with self._lock:
            self._conn.executemany(
                "INSERT INTO timeline_events VALUES "
                "(?,?,?,?,?,?,?,?,?,?,?,?,?)",
                rows,
            )
            self._conn.commit()

    def timeline_events(
        self, job: str, limit: int = 10000
    ) -> List[Dict]:
        """Newest ``limit`` timeline records, oldest first (ready for
        ``compute_ledger`` / ``export_chrome_trace``)."""
        with self._lock:
            rows = self._conn.execute(
                "SELECT node, rank, inc, name, ph, wall, mono, dur, "
                "sid, pid, labels FROM ("
                "  SELECT * FROM timeline_events WHERE job = ?"
                "  ORDER BY wall DESC LIMIT ?"
                ") ORDER BY wall ASC",
                (job, limit),
            ).fetchall()
        out = []
        for (node, rank, inc, name, ph, wall, mono, dur, sid, pid,
             labels) in rows:
            rec = {
                "name": name,
                "ph": ph,
                "wall": wall,
                "mono": mono,
                "job": job,
                "node": node,
                "rank": rank,
                "inc": inc,
                "pid": pid,
            }
            if dur is not None:
                rec["dur"] = dur
            if sid is not None:
                rec["sid"] = sid
            try:
                parsed = json.loads(labels) if labels else {}
            except json.JSONDecodeError:
                parsed = {}
            if parsed:
                rec["labels"] = parsed
            out.append(rec)
        return out

    # ------------------------------------------------------- hygiene
    def prune(self, max_age_s: float, job: Optional[str] = None):
        """Drop rows older than ``max_age_s``; with ``job`` given,
        only that job's rows (a finished job's master cleans up after
        itself without touching its neighbours' history in a shared
        db)."""
        cutoff = time.time() - max_age_s
        with self._lock:
            for table in (
                "strategy_measurements",
                "speed_samples",
                "node_events",
                "timeline_events",
            ):
                q = f"DELETE FROM {table} WHERE created_at < ?"  # noqa: S608 - fixed table names
                args: List = [cutoff]
                if job is not None:
                    q += " AND job = ?"
                    args.append(job)
                self._conn.execute(q, args)
            self._conn.commit()

    def close(self):
        with self._lock:
            self._conn.close()


_default_store: Optional[BrainDatastore] = None


def get_default_datastore() -> Optional[BrainDatastore]:
    """Process-wide datastore, enabled by ``DLROVER_TPU_BRAIN_DB``
    (the master sets it; absent = history stays in-memory only)."""
    global _default_store
    if _default_store is None:
        path = os.getenv("DLROVER_TPU_BRAIN_DB", "")
        if path:
            _default_store = BrainDatastore(path)
    return _default_store
