"""Master failover: durable control-plane journaling and replay.

A master crash must not be a job crash (ROADMAP item 1; DLRover's
ElasticJob controller recreates a failed master pod and agents simply
reattach, PAPER.md §1).  Everything the agents depend on — rendezvous
rounds, KV contents, in-flight shard leases, the node table — lives in
master memory; this module makes it durable:

- every state-changing mutation of ``KVStoreService``,
  ``RendezvousManager``, ``TaskManager`` and ``JobManager`` journals
  through :class:`ControlPlaneJournal` into the sqlite Brain
  (``control_journal`` table, write-behind — the mutating RPC never
  blocks on an fsync);
- a periodic COMPACTED snapshot (``control_snapshots``) folds the
  journal: recovery cost is bounded by one snapshot + one linger
  window of entries, not job lifetime;
- on startup :meth:`ControlPlaneJournal.recover` replays
  snapshot-then-journal into the live components BEFORE the gRPC
  server opens, so the first reconnecting agent already sees the same
  rendezvous round, the same KV keys and its shard leases re-queued
  (unacked leases go back to todo exactly like the timeout path).

Journal records are IDEMPOTENT by construction (full-state records
for rendezvous/tasks/nodes, result-valued sets for KV), so the
snapshot seq only needs to be a low-water mark: replaying an entry
the snapshot already contains is a no-op.

The whole subsystem is kill-switched by ``DLROVER_TPU_MASTER_FAILOVER=0``
and inert when no Brain db is configured.
"""

import threading
import time
from typing import Dict, Optional

from dlrover_tpu.common.env import control_snapshot_interval_s
from dlrover_tpu.common.log import default_logger as logger

#: component keys as they appear in the journal/snapshot
KV = "kv"
RDZV_PREFIX = "rdzv/"
TASKS = "tasks"
NODES = "nodes"
#: the Brain auto-scaler's hysteresis/cooldown/in-flight state — a
#: failed-over master must not forget a just-issued shrink and
#: immediately re-grow (flip-flop), and an in-flight planned action
#: must resume or be safely abandoned, never silently dropped
BRAIN = "brain"
#: the deep-capture coordinator's cooldown anchors + in-flight
#: directives — a failed-over master re-arms a pending capture under
#: the SAME id instead of losing it (or double-firing a new one)
CAPTURE = "capture"


class ControlPlaneJournal:
    """Wires the master components to the datastore journal and owns
    the snapshot/recover lifecycle for one job."""

    def __init__(
        self,
        store,
        job: str,
        kv_store=None,
        rdzv_managers: Optional[Dict[str, object]] = None,
        task_manager=None,
        job_manager=None,
        brain=None,
        capture=None,
        snapshot_interval_s: Optional[float] = None,
    ):
        self._store = store
        self._job = job
        self._kv = kv_store
        self._rdzv = dict(rdzv_managers or {})
        self._tasks = task_manager
        self._nodes = job_manager
        self._brain = brain
        self._capture = capture
        self._interval = (
            control_snapshot_interval_s()
            if snapshot_interval_s is None
            else snapshot_interval_s
        )
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: journaling errors must never break the serving path; after
        #: the first failure the journal goes quiet (logged once)
        self._broken = False
        #: self-telemetry: when the last compacted snapshot landed
        #: (mono) and how long it took — a stale snapshot means the
        #: next failover replays a long journal tail
        self._last_snapshot_mono = 0.0
        self._last_snapshot_s = 0.0
        self._last_snapshot_seq = 0

    # ------------------------------------------------------ recording
    def record(self, component: str, op: str, args: dict):
        if self._broken:
            return
        try:
            self._store.journal_append(self._job, component, op, args)
        except Exception as e:  # noqa: BLE001
            self._broken = True
            logger.error(
                "control-plane journal broken (durability lost, "
                "serving continues): %s", e,
            )

    def _cb(self, component: str):
        return lambda op, args: self.record(component, op, args)

    def attach(self):
        """Hook every component's journal callback."""
        if self._kv is not None:
            self._kv.set_journal(self._cb(KV))
        for name, manager in self._rdzv.items():
            manager.set_journal(self._cb(RDZV_PREFIX + name))
        if self._tasks is not None:
            self._tasks.set_journal(self._cb(TASKS))
        if self._nodes is not None:
            self._nodes.set_journal(self._cb(NODES))
        if self._brain is not None:
            self._brain.set_journal(self._cb(BRAIN))
        if self._capture is not None:
            self._capture.set_journal(self._cb(CAPTURE))

    def detach(self):
        if self._kv is not None:
            self._kv.set_journal(None)
        for manager in self._rdzv.values():
            manager.set_journal(None)
        if self._tasks is not None:
            self._tasks.set_journal(None)
        if self._nodes is not None:
            self._nodes.set_journal(None)
        if self._brain is not None:
            self._brain.set_journal(None)
        if self._capture is not None:
            self._capture.set_journal(None)

    # ------------------------------------------------------- recovery
    def recover(self) -> dict:
        """Replay snapshot + journal into the live components; call
        BEFORE ``attach`` (replay must not re-journal itself) and
        before the gRPC server opens.  Returns replay stats."""
        t0 = time.monotonic()
        snapshot, snap_seq = self._store.load_control_snapshot(
            self._job
        )
        if snapshot:
            self._restore_component_states(snapshot)
        entries = self._store.journal_entries(
            self._job, since_seq=snap_seq
        )
        for _seq, component, op, args in entries:
            try:
                self._route(component, op, args)
            except Exception as e:  # noqa: BLE001
                logger.warning(
                    "journal replay: %s/%s failed (%s); skipped",
                    component, op, e,
                )
        stats = {
            "snapshot_seq": snap_seq,
            "replayed": len(entries),
            "recover_s": round(time.monotonic() - t0, 4),
        }
        if snapshot or entries:
            logger.info(
                "control plane recovered: snapshot@%s + %s journal "
                "records in %.3fs",
                snap_seq, len(entries), stats["recover_s"],
            )
        return stats

    def _restore_component_states(self, snapshot: dict):
        states = snapshot.get("components") or {}
        for key, state in states.items():
            target = self._component(key)
            if target is None:
                logger.warning(
                    "snapshot names unknown component %r; skipped", key
                )
                continue
            try:
                target.restore_state(state)
            except Exception as e:  # noqa: BLE001
                logger.warning(
                    "snapshot restore for %s failed: %s", key, e
                )

    def _component(self, key: str):
        if key == KV:
            return self._kv
        if key == TASKS:
            return self._tasks
        if key == NODES:
            return self._nodes
        if key == BRAIN:
            return self._brain
        if key == CAPTURE:
            return self._capture
        if key.startswith(RDZV_PREFIX):
            return self._rdzv.get(key[len(RDZV_PREFIX):])
        return None

    def _route(self, component: str, op: str, args: dict):
        target = self._component(component)
        if target is None:
            logger.warning(
                "journal names unknown component %r; skipped",
                component,
            )
            return
        if hasattr(target, "apply_journal_op"):
            target.apply_journal_op(op, args)
        elif op == "state":
            target.restore_state(args)

    # ------------------------------------------------------- snapshot
    def snapshot_now(self):
        """One compacted snapshot: capture the pre-export journal seq
        as the low-water mark (mutations racing the export are both in
        the export AND replayed — harmless, records are idempotent),
        export every component, persist, prune."""
        if self._broken:
            return
        t0 = time.monotonic()
        try:
            seq = self._store.journal_seq(self._job)
            components = {}
            if self._kv is not None:
                components[KV] = self._kv.export_state()
            for name, manager in self._rdzv.items():
                components[RDZV_PREFIX + name] = (
                    manager.export_state()
                )
            if self._tasks is not None:
                components[TASKS] = self._tasks.export_state()
            if self._nodes is not None:
                components[NODES] = self._nodes.export_state()
            if self._brain is not None:
                components[BRAIN] = self._brain.export_state()
            if self._capture is not None:
                components[CAPTURE] = self._capture.export_state()
            self._store.save_control_snapshot(
                self._job, {"components": components}, seq
            )
            self._last_snapshot_mono = time.monotonic()
            self._last_snapshot_s = (
                self._last_snapshot_mono - t0
            )
            self._last_snapshot_seq = seq
        except Exception as e:  # noqa: BLE001
            logger.warning("control snapshot failed: %s", e)

    def health(self) -> dict:
        """Snapshot vitals for the master's self-telemetry: age (how
        long the journal tail a failover would replay has been
        growing) and duration of the last compacted snapshot.  Age is
        None until the first snapshot landed."""
        last = self._last_snapshot_mono
        return {
            "snapshot_age_s": (
                round(time.monotonic() - last, 3) if last > 0
                else None
            ),
            "snapshot_duration_s": round(self._last_snapshot_s, 4),
            "snapshot_seq": self._last_snapshot_seq,
            "interval_s": self._interval,
            "broken": self._broken,
        }

    def _loop(self):
        while not self._stopped.wait(self._interval):
            self.snapshot_now()

    def start(self):
        self._thread = threading.Thread(
            target=self._loop,
            name="control-plane-snapshot",
            daemon=True,
        )
        self._thread.start()

    def stop(self, retire: bool = False):
        """Stop the snapshot loop.  ``retire=False`` (master-only
        shutdown, e.g. a handover): final compacted snapshot, the next
        incarnation resumes this state.  ``retire=True`` (the JOB
        ended): drop the journal/snapshot and bump the job epoch so a
        future run under the same Brain db + job name starts FRESH —
        replaying a finished job's exhausted datasets and stale KV
        keys into a new job would silently end it at step 0 — and any
        straggler agent of the old run is fenced into a refresh.  A
        crash skips this method entirely; that's what the journal is
        for."""
        self._stopped.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if not retire:
            self.snapshot_now()
            return
        try:
            self._store.bump_job_epoch(self._job)
            logger.info(
                "control-plane state for job %r retired (job ended)",
                self._job,
            )
        except Exception as e:  # noqa: BLE001
            logger.warning("control-plane retire failed: %s", e)
