"""Error monitor: classify node/process failures and recommend the
recovery rung.

Reference parity: ``dlrover/python/master/monitor/error_monitor.py``
(process/node error-log handling) + the relaunch-decision inputs of
``_should_relaunch`` (``dist_job_manager.py:546``).  The reference's
production finding (``docs/blogs/flash_checkpoint.md:88``): ~75% of
faults are recoverable by a process restart — so classification is
what keeps the recovery ladder cheap: restart the process for software
faults, replace the pod for hardware faults, grow memory for OOM, stop
the job for deterministic user-code errors.
"""

import re
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.common.log import default_logger as logger


class ErrorKind:
    OOM = "oom"
    HARDWARE = "hardware"
    NETWORK = "network"
    USER_CODE = "user_code"
    PREEMPTION = "preemption"
    UNKNOWN = "unknown"


class RecoveryAction:
    RESTART_PROCESS = "restart_process"
    RELAUNCH_NODE = "relaunch_node"
    GROW_MEMORY = "grow_memory"
    STOP_JOB = "stop_job"


# first match wins; patterns over stderr/log excerpts and exit reasons
_CLASSIFIERS: List[Tuple[str, str]] = [
    (r"RESOURCE_EXHAUSTED|out of memory|OOM|Killed.*memory", ErrorKind.OOM),
    (
        r"preempt|maintenance event|TERMINATED_BY_SYSTEM|spot.*reclaim",
        ErrorKind.PREEMPTION,
    ),
    (
        r"hbm.*(error|fail)|uncorrectable|device.*(lost|unhealthy)|"
        r"libtpu.*abort|chip.*fail|ICI.*(down|error)",
        ErrorKind.HARDWARE,
    ),
    (
        r"connection (refused|reset)|deadline exceeded|unavailable|"
        r"socket.*(closed|timeout)|coordinator.*unreachable",
        ErrorKind.NETWORK,
    ),
    (
        r"Traceback \(most recent call last\)|AssertionError|KeyError|"
        r"ValueError|TypeError|ModuleNotFoundError",
        ErrorKind.USER_CODE,
    ),
]

_ACTION_FOR: Dict[str, str] = {
    ErrorKind.OOM: RecoveryAction.GROW_MEMORY,
    ErrorKind.PREEMPTION: RecoveryAction.RELAUNCH_NODE,
    ErrorKind.HARDWARE: RecoveryAction.RELAUNCH_NODE,
    ErrorKind.NETWORK: RecoveryAction.RESTART_PROCESS,
    ErrorKind.USER_CODE: RecoveryAction.STOP_JOB,
    ErrorKind.UNKNOWN: RecoveryAction.RESTART_PROCESS,
}


def classify_error(error_data: str) -> str:
    for pattern, kind in _CLASSIFIERS:
        if re.search(pattern, error_data, re.IGNORECASE):
            return kind
    return ErrorKind.UNKNOWN


@dataclass
class ErrorRecord:
    node_id: int
    node_type: str
    kind: str
    excerpt: str
    timestamp: float = field(default_factory=time.time)


class ErrorMonitor:
    """Collects error reports, classifies them, and answers the job
    manager's "how should this failure be recovered?" question."""

    MAX_RECORDS = 1000  # bounded history: a flapping link must not
    # grow master memory for weeks

    def __init__(self, user_code_threshold: int = 3,
                 window_secs: float = 1800.0):
        import collections

        self._lock = threading.Lock()
        self._records: "collections.deque[ErrorRecord]" = (
            collections.deque(maxlen=self.MAX_RECORDS)
        )
        # repeated deterministic user-code failures stop the job
        self._user_code_threshold = user_code_threshold
        self._window = window_secs

    def report(self, node_id: int, node_type: str,
               error_data: str) -> str:
        """Record and classify one failure; returns the recommended
        RecoveryAction."""
        kind = classify_error(error_data or "")
        with self._lock:
            self._records.append(
                ErrorRecord(
                    node_id=node_id,
                    node_type=node_type,
                    kind=kind,
                    excerpt=(error_data or "")[:500],
                )
            )
        action = _ACTION_FOR[kind]
        if kind == ErrorKind.USER_CODE:
            # one traceback can still be environmental; repeated
            # same-class failures of the SAME node across restarts are
            # deterministic -> stop the job instead of burning
            # restarts.  (Counting across nodes would let three
            # unrelated transient tracebacks on a 100-worker job kill
            # everything.)
            if self._recent_count(
                ErrorKind.USER_CODE, node_id=node_id
            ) < self._user_code_threshold:
                action = RecoveryAction.RESTART_PROCESS
        logger.info(
            "node %s failure classified %s -> %s", node_id, kind, action
        )
        return action

    def _recent_count(self, kind: str,
                      node_id: Optional[int] = None) -> int:
        cutoff = time.time() - self._window
        with self._lock:
            return sum(
                1
                for r in self._records
                if r.kind == kind
                and r.timestamp >= cutoff
                and (node_id is None or r.node_id == node_id)
            )

    def history(self, node_id: Optional[int] = None) -> List[ErrorRecord]:
        with self._lock:
            return [
                r
                for r in self._records
                if node_id is None or r.node_id == node_id
            ]

    def summary(self) -> Dict[str, int]:
        with self._lock:
            out: Dict[str, int] = {}
            for r in self._records:
                out[r.kind] = out.get(r.kind, 0) + 1
            return out
