"""The master RPC servicer: dispatch tables for ``get`` and ``report``.

Reference parity: ``dlrover/python/master/servicer.py:72,99,650``; the
full dispatch surface is the parity checklist in SURVEY.md Appendix A.
Every request type routes to the backing component (task manager,
rendezvous managers, KV store, job manager, speed monitor, diagnosis).
"""

import time
from typing import Optional

from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.comm import build_master_server
from dlrover_tpu.common.constants import (
    RendezvousName,
    TrainingLoopStatus,
)
from dlrover_tpu.common.log import default_logger as logger


class MasterServicer:
    def __init__(
        self,
        task_manager=None,
        job_manager=None,
        speed_monitor=None,
        rdzv_managers=None,
        kv_store=None,
        diagnosis_manager=None,
        sync_service=None,
        timeline_aggregator=None,
    ):
        self._task_manager = task_manager
        self._job_manager = job_manager
        self._speed_monitor = speed_monitor
        self._rdzv_managers = rdzv_managers or {}
        self._kv_store = kv_store
        self._diagnosis_manager = diagnosis_manager
        self._sync_service = sync_service
        self._timeline_aggregator = timeline_aggregator
        self._start_training_time = 0.0

    # ------------------------------------------------------------------ get
    def get(self, envelope: msg.Envelope) -> Optional[msg.Message]:
        request = msg.deserialize_message(envelope.data)
        node_id, node_type = envelope.node_id, envelope.node_type
        if isinstance(request, msg.TaskRequest):
            return self._get_task(node_id, request)
        if isinstance(request, msg.ShardCheckpointRequest):
            return self._task_manager.get_dataset_checkpoint(
                request.dataset_name
            )
        if isinstance(request, msg.RunningNodesRequest):
            return msg.RunningNodes(
                nodes=self._job_manager.get_running_nodes()
            )
        if isinstance(request, msg.JoinRendezvousRequest):
            return self._join_rendezvous(request)
        if isinstance(request, msg.WaitingNodeNumRequest):
            manager = self._rdzv_managers.get(
                request.rdzv_name or RendezvousName.ELASTIC_TRAINING
            )
            return msg.WaitingNodeNum(
                waiting_num=manager.num_nodes_waiting() if manager else 0
            )
        if isinstance(request, msg.NetworkReadyRequest):
            return self._check_fault_node()
        if isinstance(request, msg.StragglerExistRequest):
            return self._check_straggler()
        if isinstance(request, msg.CommWorldRequest):
            return self._get_comm_world(request)
        if isinstance(request, msg.KeyValuePair):
            return msg.KeyValuePair(
                key=request.key, value=self._kv_store.get(request.key)
            )
        if isinstance(request, msg.TrainingStatusRequest):
            if self._task_manager and self._task_manager.training_started():
                status = TrainingLoopStatus.START
            else:
                status = TrainingLoopStatus.PENDING
            return msg.TrainingStatus(status=status)
        if isinstance(request, msg.ParallelConfigRequest):
            if self._job_manager:
                return self._job_manager.get_paral_config()
            return msg.ParallelConfig()
        if isinstance(request, msg.CheckHardwareResetRequest):
            restart = False
            if self._job_manager:
                restart = self._job_manager.should_restart_node(
                    node_type, node_id
                )
            return msg.ParallelConfig(restart=restart)
        if isinstance(request, msg.PsNodesRequest):
            return msg.PsNodes()
        if isinstance(request, msg.ClusterVersionRequest):
            return msg.ClusterVersion()
        if isinstance(request, msg.ElasticRunConfigRequest):
            return msg.ElasticRunConfig()
        if isinstance(request, msg.BrainQueryRequest):
            return self._brain_query(request)
        if isinstance(request, msg.TimelineQueryRequest):
            return self._timeline_query(request)
        logger.warning("unhandled get request: %r", request)
        return None

    def _timeline_query(
        self, request: msg.TimelineQueryRequest
    ) -> msg.TimelineQueryResponse:
        agg = self._timeline_aggregator
        if agg is None:
            return msg.TimelineQueryResponse(available=False)
        return msg.TimelineQueryResponse(
            ledger=agg.ledger(),
            events=agg.events(request.limit) if request.limit else [],
            available=True,
        )

    def _brain_query(
        self, request: msg.BrainQueryRequest
    ) -> msg.BrainQueryResponse:
        from dlrover_tpu.master.datastore import get_default_datastore

        store = get_default_datastore()
        if store is None:
            return msg.BrainQueryResponse(available=False)
        if request.kind == "speed":
            payload = {
                "speed": store.speed_history(request.job)
            }
        elif request.kind == "node_events":
            payload = {
                "events": store.node_events(
                    request.job, limit=request.limit
                )
            }
        elif request.kind == "workloads":
            payload = {"workloads": store.measured_workloads()}
        elif request.kind == "measurements":
            # cross-job calibration: ANY job's strategy service can
            # pull this fleet's history for a workload signature
            # (ref: the Go Brain serving all jobs' metrics,
            # dlrover/go/brain/pkg/datastore/dbbase/recorder.go:280)
            payload = {
                "measurements": store.load_measurements(
                    request.workload, limit=request.limit
                )
            }
        else:
            return msg.BrainQueryResponse(available=False)
        return msg.BrainQueryResponse(
            payload=payload, available=True
        )

    def _get_task(self, node_id: int, request: msg.TaskRequest) -> msg.Task:
        if not self._start_training_time:
            self._start_training_time = time.time()
            if self._speed_monitor:
                self._speed_monitor.set_start_timestamp()
        return self._task_manager.get_task(node_id, request.dataset_name)

    def _join_rendezvous(self, request: msg.JoinRendezvousRequest):
        manager = self._rdzv_managers.get(
            request.rdzv_name or RendezvousName.ELASTIC_TRAINING
        )
        if manager is None:
            return msg.RendezvousState(round=-1)
        rdzv_round = manager.join_rendezvous(
            request.node_rank, request.local_world_size
        )
        if request.rdzv_name == RendezvousName.NETWORK_CHECK:
            # joining a network check clears the training waitlist
            # bookkeeping (reference servicer.py:257-263)
            training = self._rdzv_managers.get(
                RendezvousName.ELASTIC_TRAINING
            )
            if training:
                training.remove_alive_node(request.node_rank)
        return msg.RendezvousState(round=rdzv_round)

    def _get_comm_world(self, request: msg.CommWorldRequest):
        manager = self._rdzv_managers.get(
            request.rdzv_name or RendezvousName.ELASTIC_TRAINING
        )
        if manager is None:
            return msg.CommWorld()
        rdzv_round, group, world = manager.get_comm_world(request.node_id)
        return msg.CommWorld(
            rdzv_name=request.rdzv_name,
            round=rdzv_round,
            group=group,
            world=world,
        )

    def _check_fault_node(self):
        manager = self._rdzv_managers.get(RendezvousName.NETWORK_CHECK)
        if manager is None:
            return msg.NetworkCheckResult()
        nodes, reason = manager.check_fault_node()
        return msg.NetworkCheckResult(nodes=nodes, reason=reason)

    def _check_straggler(self):
        manager = self._rdzv_managers.get(RendezvousName.NETWORK_CHECK)
        if manager is None:
            return msg.NetworkCheckResult()
        nodes, reason = manager.check_straggler()
        return msg.NetworkCheckResult(nodes=nodes, reason=reason)

    # --------------------------------------------------------------- report
    def report(self, envelope: msg.Envelope) -> msg.BoolResponse:
        request = msg.deserialize_message(envelope.data)
        node_id, node_type = envelope.node_id, envelope.node_type
        success = False
        try:
            success = self._dispatch_report(node_id, node_type, request)
        except Exception as e:  # noqa: BLE001
            logger.error("report handler error for %r: %s", request, e)
            return msg.BoolResponse(success=False, reason=repr(e))
        return msg.BoolResponse(success=bool(success))

    def _dispatch_report(self, node_id, node_type, request) -> bool:
        if isinstance(request, msg.DatasetShardParams):
            self._task_manager.new_dataset(request)
            return True
        if isinstance(request, msg.ShardCheckpoint):
            return self._task_manager.restore_dataset_from_checkpoint(
                request
            )
        if isinstance(request, msg.TaskResult):
            return self._task_manager.report_task_status(
                request.dataset_name,
                request.task_id,
                success=not request.err_message,
            )
        if isinstance(request, msg.ResourceStats):
            if self._job_manager:
                self._job_manager.update_node_resource_usage(
                    node_type,
                    node_id,
                    request.cpu_percent,
                    request.memory_mb,
                    request.tpu_stats,
                )
            return True
        if isinstance(request, msg.GlobalStep):
            if self._speed_monitor:
                self._speed_monitor.collect_global_step(
                    request.step, request.timestamp or time.time()
                )
            return True
        if isinstance(request, msg.NodeAddress):
            if self._job_manager:
                self._job_manager.update_node_address(
                    request.node_type, request.node_id, request.addr
                )
            return True
        if isinstance(request, msg.NodeTopology):
            manager = self._rdzv_managers.get(
                RendezvousName.ELASTIC_TRAINING
            )
            if manager is not None and hasattr(
                manager, "set_node_topology"
            ):
                manager.set_node_topology(
                    request.node_rank, tuple(request.levels)
                )
            return True
        if isinstance(request, msg.NetworkStatus):
            manager = self._rdzv_managers.get(
                RendezvousName.NETWORK_CHECK
            )
            if manager:
                manager.report_network_status(
                    request.node_rank,
                    request.succeeded,
                    request.elapsed_time,
                )
            return True
        if isinstance(request, msg.NodeEventMessage):
            return True
        if isinstance(request, msg.NodeFailure):
            if self._job_manager:
                self._job_manager.handle_training_failure(
                    node_type,
                    node_id,
                    request.restart_count,
                    request.error_data,
                    request.level,
                )
            return True
        if isinstance(request, msg.RendezvousParams):
            for manager in self._rdzv_managers.values():
                manager.update_rdzv_params(
                    request.min_nodes,
                    request.max_nodes,
                    request.waiting_timeout,
                    request.node_unit,
                )
            return True
        if isinstance(request, msg.KeyValuePair):
            self._kv_store.set(request.key, request.value)
            return True
        if isinstance(request, msg.ParallelConfig):
            if self._job_manager:
                self._job_manager.update_paral_config(request)
            return True
        if isinstance(request, msg.HeartBeat):
            if self._job_manager:
                self._job_manager.collect_node_heartbeat(
                    node_type, node_id, request.timestamp or time.time()
                )
            return True
        if isinstance(request, msg.NodeCheckpointState):
            manager = self._rdzv_managers.get(
                RendezvousName.ELASTIC_TRAINING
            )
            if manager:
                return manager.sync_ckpt_nodes(node_id, request.step)
            return False
        if isinstance(request, msg.ModelInfo):
            return True
        if isinstance(request, msg.DiagnosisReportData):
            if self._diagnosis_manager:
                from dlrover_tpu.master.diagnosis import DiagnosisData

                self._diagnosis_manager.collect_data(
                    DiagnosisData(
                        data_type=request.data_cls,
                        content=request.data_content,
                        node_rank=request.node_rank,
                    )
                )
            return True
        if isinstance(request, msg.TimelineEventsReport):
            if self._timeline_aggregator is not None:
                self._timeline_aggregator.add_events(
                    node_id, request.events
                )
            return True
        if isinstance(request, msg.Event):
            logger.info(
                "event from %s-%s: %s %s %s",
                node_type, node_id,
                request.event_type, request.action, request.msg,
            )
            return True
        if isinstance(request, (msg.SyncJoin, msg.SyncFinish,
                                msg.SyncBarrier)):
            if self._sync_service:
                return self._sync_service.handle(node_type, node_id,
                                                 request)
            return True
        if isinstance(request, msg.PsReady):
            return True
        if isinstance(request, msg.SucceededRequest):
            return True
        logger.warning("unhandled report: %r", request)
        return False


def create_master_service(port: int, servicer: MasterServicer,
                          max_workers: int = 64):
    """Build the gRPC server wired to the servicer."""
    return build_master_server(
        port, servicer.report, servicer.get, max_workers=max_workers
    )
