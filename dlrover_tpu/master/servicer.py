"""The master RPC servicer: dispatch tables for ``get`` and ``report``.

Reference parity: ``dlrover/python/master/servicer.py:72,99,650``; the
full dispatch surface is the parity checklist in SURVEY.md Appendix A.
Every request type routes to the backing component (task manager,
rendezvous managers, KV store, job manager, speed monitor, diagnosis).
"""

import threading
import time
from typing import Optional

from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.comm import build_master_server
from dlrover_tpu.common.constants import (
    RendezvousName,
    TrainingExceptionLevel,
    TrainingLoopStatus,
)
from dlrover_tpu.common.env import (
    master_failover_enabled,
    master_workers,
)
from dlrover_tpu.common.fault_injection import maybe_crash
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.observability.metrics import record_control_rpc


class MasterServicer:
    # at most ``max_parked_waits`` (HALF the gRPC pool —
    # ``DLROVER_TPU_MASTER_WORKERS`` scales both together, 32 for the
    # default 64-worker pool) RPC workers may PARK in long-poll waits
    # at once; past the cap a wait degrades to an immediate answer
    # (the client just re-issues), so join/set/report mutations — the
    # RPCs that WAKE parked waiters — always find a free worker and
    # the pool cannot deadlock on its own waiters

    def __init__(
        self,
        task_manager=None,
        job_manager=None,
        speed_monitor=None,
        rdzv_managers=None,
        kv_store=None,
        diagnosis_manager=None,
        sync_service=None,
        timeline_aggregator=None,
        health_engine=None,
        brain=None,
        capture_coordinator=None,
        job_epoch: int = 0,
        incarnation: int = 0,
        telemetry=None,
        serving_status_fn=None,
    ):
        #: fencing identity: requests carrying a DIFFERENT job_epoch
        #: get a typed ``StaleEpoch`` answer (client refreshes and
        #: re-issues) instead of being dispatched against the wrong
        #: job generation.  incarnation is informational — it tells
        #: reconnecting clients the master restarted.
        self.job_epoch = job_epoch
        self.incarnation = incarnation
        self._task_manager = task_manager
        self._job_manager = job_manager
        self._speed_monitor = speed_monitor
        self._rdzv_managers = rdzv_managers or {}
        self._kv_store = kv_store
        self._diagnosis_manager = diagnosis_manager
        self._sync_service = sync_service
        self._timeline_aggregator = timeline_aggregator
        #: the observatory's streaming derivation engine (None =
        #: DLROVER_TPU_OBSERVATORY=0 or a pre-observatory master);
        #: heartbeats / steps / failures / resource reports tap it
        self._health_engine = health_engine
        #: the Brain auto-scaler (None = DLROVER_TPU_BRAIN=0):
        #: node directives ride the WaitingNodeNum response and its
        #: decision state joins the JobStatus snapshot
        self._brain = brain
        #: the deep-capture coordinator (None = DLROVER_TPU_PROFILE=0
        #: or observatory off): capture directives ride the SAME
        #: WaitingNodeNum piggyback (a Brain drain outranks them) and
        #: the latest capture per node joins the JobStatus snapshot
        self._capture = capture_coordinator
        self._start_training_time = 0.0
        #: lifetime RPC tally (gets + reports, batched items counted
        #: once per envelope) — the bench's server-side ground truth
        self.rpc_count = 0
        #: self-telemetry collector (None = DLROVER_TPU_SELF_OBS=0 or
        #: a pre-self-obs caller): per-RPC-kind latency/size
        #: histograms, in-flight/parked gauges, the ``master`` status
        #: section
        self._telemetry = telemetry
        #: zero-arg callable returning the serving plane's status dict
        #: (``ServingEngine.status()``); None = no co-located serving
        #: engine or DLROVER_TPU_SERVE_OBS=0 — the ``serving`` status
        #: section is simply absent (pinned pre-16 shape)
        self._serving_status_fn = serving_status_fn
        #: the parked-wait cap scales with the pool: half the workers
        #: may park, so mutations always find a free one
        self.max_parked_waits = max(master_workers() // 2, 1)
        self._wait_slots = threading.BoundedSemaphore(
            self.max_parked_waits
        )

    def _count_rpc(self):
        # benign race on +=: the tally is telemetry, not a lock target
        self.rpc_count += 1
        record_control_rpc()

    def _bounded_wait(self, wait_fn, immediate_fn):
        """Run a blocking wait under the parked-waiter cap; saturated
        ⇒ answer immediately (the client loop re-issues, with its own
        backoff) instead of parking another pool thread."""
        if not self._wait_slots.acquire(blocking=False):
            if self._telemetry is not None:
                self._telemetry.wait_rejected()
            return immediate_fn()
        if self._telemetry is not None:
            self._telemetry.wait_parked()
        try:
            # chaos hook: a kill pinned here dies with RPCs parked
            # mid-long-poll — the waiters must re-park on the next
            # incarnation, not crash
            maybe_crash("mid_long_poll")
            return wait_fn()
        finally:
            if self._telemetry is not None:
                self._telemetry.wait_unparked()
            self._wait_slots.release()

    def _fenced(self, envelope: msg.Envelope) -> Optional[msg.StaleEpoch]:
        """Typed fencing answer when the request's job_epoch doesn't
        match this master's.  ``-1`` (old clients / kill-switched
        failover) is never fenced."""
        if not master_failover_enabled():
            return None
        epoch = getattr(envelope, "job_epoch", -1)
        if epoch is None or epoch < 0 or epoch == self.job_epoch:
            return None
        return msg.StaleEpoch(
            job_epoch=self.job_epoch, incarnation=self.incarnation
        )

    @staticmethod
    def _response_bytes(response) -> Optional[int]:
        """Wire size of one response (None when there is none).  The
        extra serialize only runs with self-obs ON and control
        responses are small pickles — the histogram is worth the
        double-encode; a failure must not break the RPC."""
        if response is None:
            return None
        try:
            return len(msg.serialize_message(response))
        except Exception:  # noqa: BLE001
            return None

    # ------------------------------------------------------------------ get
    def get(self, envelope: msg.Envelope) -> Optional[msg.Message]:
        self._count_rpc()
        request = msg.deserialize_message(envelope.data)
        if self._telemetry is None:
            return self._get_dispatch(envelope, request)
        t0 = time.perf_counter()
        self._telemetry.rpc_begin()
        response = None
        try:
            response = self._get_dispatch(envelope, request)
            return response
        finally:
            self._telemetry.rpc_end(
                type(request).__name__,
                time.perf_counter() - t0,
                len(envelope.data or b""),
                self._response_bytes(response),
            )

    def _get_dispatch(
        self, envelope: msg.Envelope, request
    ) -> Optional[msg.Message]:
        node_id, node_type = envelope.node_id, envelope.node_type
        if isinstance(request, msg.ControlEpochRequest):
            # the refresh path — answered even to stale clients (it is
            # HOW they stop being stale)
            return msg.ControlEpoch(
                job_epoch=self.job_epoch,
                incarnation=self.incarnation,
            )
        stale = self._fenced(envelope)
        if stale is not None:
            return stale
        if isinstance(request, msg.TaskRequest):
            return self._get_task(node_id, request)
        if isinstance(request, msg.ShardCheckpointRequest):
            return self._task_manager.get_dataset_checkpoint(
                request.dataset_name
            )
        if isinstance(request, msg.RunningNodesRequest):
            return self._get_running_nodes(request)
        if isinstance(request, msg.JoinRendezvousRequest):
            return self._join_rendezvous(request)
        if isinstance(request, msg.WaitingNodeNumRequest):
            return self._get_waiting_num(request, node_id)
        if isinstance(request, msg.NetworkReadyRequest):
            return self._check_fault_node()
        if isinstance(request, msg.StragglerExistRequest):
            return self._check_straggler()
        if isinstance(request, msg.CommWorldRequest):
            return self._get_comm_world(request)
        if isinstance(request, msg.KVWaitRequest):
            # long-poll: park on the KV store's condition; an empty
            # value means the wait timed out (the client loops)
            value = self._bounded_wait(
                lambda: self._kv_store.wait(
                    request.key, timeout=request.wait_timeout
                ),
                lambda: self._kv_store.get(request.key),
            )
            return msg.KeyValuePair(key=request.key, value=value or b"")
        if isinstance(request, msg.KeyValuePair):
            return msg.KeyValuePair(
                key=request.key, value=self._kv_store.get(request.key)
            )
        if isinstance(request, msg.TrainingStatusRequest):
            started = bool(
                self._task_manager
                and self._task_manager.training_started()
            )
            # getattr throughout this dispatch: a pre-fast-path client
            # pickles its dataclasses WITHOUT the new fields (unpickle
            # restores __dict__, not defaults) and must keep working
            # across a rolling upgrade
            wait_timeout = getattr(request, "wait_timeout", 0.0)
            if (
                not started
                and wait_timeout > 0
                and self._task_manager is not None
            ):
                started = self._bounded_wait(
                    lambda: self._task_manager.wait_training_started(
                        wait_timeout
                    ),
                    lambda: False,
                )
            status = (
                TrainingLoopStatus.START
                if started
                else TrainingLoopStatus.PENDING
            )
            return msg.TrainingStatus(status=status)
        if isinstance(request, msg.ParallelConfigRequest):
            if self._job_manager:
                return self._job_manager.get_paral_config()
            return msg.ParallelConfig()
        if isinstance(request, msg.CheckHardwareResetRequest):
            restart = False
            if self._job_manager:
                restart = self._job_manager.should_restart_node(
                    node_type, node_id
                )
            return msg.ParallelConfig(restart=restart)
        if isinstance(request, msg.PsNodesRequest):
            return msg.PsNodes()
        if isinstance(request, msg.ClusterVersionRequest):
            return msg.ClusterVersion()
        if isinstance(request, msg.ElasticRunConfigRequest):
            return msg.ElasticRunConfig()
        if isinstance(request, msg.BrainQueryRequest):
            return self._brain_query(request)
        if isinstance(request, msg.TimelineQueryRequest):
            return self._timeline_query(request)
        if isinstance(request, msg.JobStatusRequest):
            return self._job_status(request)
        logger.warning("unhandled get request: %r", request)
        return None

    def _job_status(
        self, request: msg.JobStatusRequest
    ) -> msg.JobStatusResponse:
        """The observatory snapshot: streaming health derivations +
        the live goodput ledger + the newest diagnosis conclusions.
        ``available=False`` when the observatory is off (kill-switch)
        — the pre-observatory master had no such surface."""
        if self._health_engine is None:
            return msg.JobStatusResponse(available=False)
        status = {"health": self._health_engine.snapshot()}
        if self._timeline_aggregator is not None:
            try:
                status["ledger"] = self._timeline_aggregator.ledger()
            except Exception as e:  # noqa: BLE001 - partial status beats none
                logger.warning("status ledger failed: %s", e)
        if self._diagnosis_manager is not None and hasattr(
            self._diagnosis_manager, "recent_conclusions"
        ):
            status["conclusions"] = (
                self._diagnosis_manager.recent_conclusions(
                    getattr(request, "conclusions", 16)
                )
            )
        if self._speed_monitor is not None:
            status["speed"] = {
                "global_step": self._speed_monitor.completed_global_step,
                "records_per_sec": self._speed_monitor.running_speed(),
            }
        status["epoch"] = {
            "job_epoch": self.job_epoch,
            "incarnation": self.incarnation,
        }
        if self._brain is not None:
            try:
                status["brain"] = self._brain.status()
            except Exception as e:  # noqa: BLE001 - partial status
                logger.warning("status brain failed: %s", e)
        if self._capture is not None:
            try:
                status["profiles"] = self._capture.latest()
            except Exception as e:  # noqa: BLE001 - partial status
                logger.warning("status profiles failed: %s", e)
        if self._telemetry is not None:
            # the control plane's own vitals: RPC latency per kind,
            # pool occupancy, state growth, journal/datastore health
            # (absent under DLROVER_TPU_SELF_OBS=0 — pinned)
            try:
                status["master"] = self._telemetry.snapshot()
            except Exception as e:  # noqa: BLE001 - partial status
                logger.warning("status master section failed: %s", e)
        if self._serving_status_fn is not None:
            # the serving observatory: replica table + SLO quantiles +
            # per-replica health verdicts from the co-located engine
            try:
                status["serving"] = self._serving_status_fn()
            except Exception as e:  # noqa: BLE001 - partial status
                logger.warning("status serving section failed: %s", e)
        return msg.JobStatusResponse(status=status, available=True)

    def _timeline_query(
        self, request: msg.TimelineQueryRequest
    ) -> msg.TimelineQueryResponse:
        agg = self._timeline_aggregator
        if agg is None:
            return msg.TimelineQueryResponse(available=False)
        return msg.TimelineQueryResponse(
            ledger=agg.ledger(),
            events=agg.events(request.limit) if request.limit else [],
            available=True,
        )

    def _brain_query(
        self, request: msg.BrainQueryRequest
    ) -> msg.BrainQueryResponse:
        from dlrover_tpu.master.datastore import get_default_datastore

        store = get_default_datastore()
        if store is None:
            return msg.BrainQueryResponse(available=False)
        if request.kind == "speed":
            payload = {
                "speed": store.speed_history(request.job)
            }
        elif request.kind == "node_events":
            payload = {
                "events": store.node_events(
                    request.job, limit=request.limit
                )
            }
        elif request.kind == "workloads":
            payload = {"workloads": store.measured_workloads()}
        elif request.kind == "profiles":
            payload = {
                "profiles": store.profiles(
                    request.job, limit=request.limit
                )
            }
        elif request.kind == "measurements":
            # cross-job calibration: ANY job's strategy service can
            # pull this fleet's history for a workload signature
            # (ref: the Go Brain serving all jobs' metrics,
            # dlrover/go/brain/pkg/datastore/dbbase/recorder.go:280)
            payload = {
                "measurements": store.load_measurements(
                    request.workload, limit=request.limit
                )
            }
        else:
            return msg.BrainQueryResponse(available=False)
        return msg.BrainQueryResponse(
            payload=payload, available=True
        )

    def _get_task(self, node_id: int, request: msg.TaskRequest) -> msg.Task:
        if not self._start_training_time:
            self._start_training_time = time.time()
            if self._speed_monitor:
                self._speed_monitor.set_start_timestamp()
        wait_timeout = getattr(request, "wait_timeout", 0.0)
        if wait_timeout > 0:
            return self._bounded_wait(
                lambda: self._task_manager.wait_task(
                    node_id, request.dataset_name, wait_timeout
                ),
                lambda: self._task_manager.get_task(
                    node_id, request.dataset_name
                ),
            )
        return self._task_manager.get_task(node_id, request.dataset_name)

    def _get_running_nodes(self, request: msg.RunningNodesRequest):
        if self._job_manager is None:
            return msg.RunningNodes()
        version = self._job_manager.nodes_version
        req_version = getattr(request, "version", -1)
        if req_version >= 0 and req_version == version:
            return msg.NotModified(version=version)
        return msg.RunningNodes(
            nodes=self._job_manager.get_running_nodes(),
            version=version,
        )

    def _get_waiting_num(self, request: msg.WaitingNodeNumRequest,
                         node_id: int = -1):
        manager = self._rdzv_managers.get(
            request.rdzv_name or RendezvousName.ELASTIC_TRAINING
        )
        if manager is None:
            return msg.WaitingNodeNum(waiting_num=0)
        # Brain directive piggyback: a pending planned action for THIS
        # node short-circuits the long poll (the agent must act now,
        # not after the park) and is consumed on delivery
        directive = None
        if self._brain is not None and node_id >= 0:
            directive = self._brain.directives.take(node_id)
        if directive is None and self._capture is not None and (
            node_id >= 0
        ):
            # a deep-capture request rides the same slot; a Brain
            # drain outranks it (the node is leaving anyway — its
            # capture stays pending and expires with the cooldown)
            directive = self._capture.directives.take(node_id)
        wait_timeout = getattr(request, "wait_timeout", 0.0)
        if directive is not None:
            waiting = manager.num_nodes_waiting()
            action, reason, decision_id = directive
            return msg.WaitingNodeNum(
                waiting_num=waiting,
                action=action,
                action_reason=reason,
                action_id=decision_id,
            )
        if wait_timeout > 0:
            waiting = self._bounded_wait(
                lambda: manager.wait_num_nodes(
                    last_num=getattr(request, "last_num", -1),
                    timeout=wait_timeout,
                ),
                manager.num_nodes_waiting,
            )
        else:
            waiting = manager.num_nodes_waiting()
        return msg.WaitingNodeNum(waiting_num=waiting)

    def _join_rendezvous(self, request: msg.JoinRendezvousRequest):
        manager = self._rdzv_managers.get(
            request.rdzv_name or RendezvousName.ELASTIC_TRAINING
        )
        if manager is None:
            return msg.RendezvousState(round=-1)
        rdzv_round = manager.join_rendezvous(
            request.node_rank, request.local_world_size
        )
        if request.rdzv_name == RendezvousName.NETWORK_CHECK:
            # joining a network check clears the training waitlist
            # bookkeeping (reference servicer.py:257-263)
            training = self._rdzv_managers.get(
                RendezvousName.ELASTIC_TRAINING
            )
            if training:
                training.remove_alive_node(request.node_rank)
        return msg.RendezvousState(round=rdzv_round)

    def _get_comm_world(self, request: msg.CommWorldRequest):
        manager = self._rdzv_managers.get(
            request.rdzv_name or RendezvousName.ELASTIC_TRAINING
        )
        if manager is None:
            return msg.CommWorld()
        wait_timeout = getattr(request, "wait_timeout", 0.0)
        req_version = getattr(request, "version", -1)
        if wait_timeout > 0:
            rdzv_round, group, world, version = self._bounded_wait(
                lambda: manager.wait_comm_world(
                    request.node_id,
                    version=req_version,
                    timeout=wait_timeout,
                ),
                lambda: manager.get_comm_world_versioned(
                    request.node_id
                ),
            )
        else:
            rdzv_round, group, world, version = (
                manager.get_comm_world_versioned(request.node_id)
            )
        if (
            req_version >= 0
            and req_version == version
            and world
        ):
            # the client's cached world is still this exact state
            return msg.NotModified(version=version)
        return msg.CommWorld(
            rdzv_name=request.rdzv_name,
            round=rdzv_round,
            group=group,
            world=world,
            version=version,
        )

    def _check_fault_node(self):
        manager = self._rdzv_managers.get(RendezvousName.NETWORK_CHECK)
        if manager is None:
            return msg.NetworkCheckResult()
        nodes, reason = manager.check_fault_node()
        return msg.NetworkCheckResult(nodes=nodes, reason=reason)

    def _check_straggler(self):
        manager = self._rdzv_managers.get(RendezvousName.NETWORK_CHECK)
        if manager is None:
            return msg.NetworkCheckResult()
        nodes, reason = manager.check_straggler()
        return msg.NetworkCheckResult(nodes=nodes, reason=reason)

    # --------------------------------------------------------------- report
    def report(self, envelope: msg.Envelope):
        self._count_rpc()
        if self._telemetry is None:
            return self._report_dispatch(envelope)[1]
        t0 = time.perf_counter()
        self._telemetry.rpc_begin()
        kind, response = "?", None
        try:
            kind, response = self._report_dispatch(envelope)
            return response
        finally:
            self._telemetry.rpc_end(
                kind,
                time.perf_counter() - t0,
                len(envelope.data or b""),
                self._response_bytes(response),
            )

    def _report_dispatch(self, envelope: msg.Envelope):
        """Fence FIRST, deserialize second (the pre-self-obs order):
        a stale client must get its typed ``StaleEpoch`` even when
        its payload no longer unpickles across a rolling upgrade, and
        a fenced request must not pay deserialization.  Returns
        ``(kind, response)`` so the telemetry wrapper can label the
        series without deserializing itself."""
        stale = self._fenced(envelope)
        if stale is not None:
            return "StaleEpoch", stale
        request = msg.deserialize_message(envelope.data)
        node_id, node_type = envelope.node_id, envelope.node_type
        kind = type(request).__name__
        success = False
        try:
            success = self._dispatch_report(node_id, node_type, request)
        except Exception as e:  # noqa: BLE001
            logger.error("report handler error for %r: %s", request, e)
            return kind, msg.BoolResponse(
                success=False, reason=repr(e)
            )
        return kind, msg.BoolResponse(success=bool(success))

    def _dispatch_report(self, node_id, node_type, request) -> bool:
        if isinstance(request, msg.BatchedReport):
            # coalesced delta reporting: dispatch IN ORDER; every item
            # runs even after a failure (dropping the tail would lose
            # reports the client thinks are delivered), the ack is the
            # conjunction
            ok = True
            for item in request.items:
                try:
                    ok = self._dispatch_report(
                        node_id, node_type, item
                    ) and ok
                except Exception as e:  # noqa: BLE001
                    logger.error(
                        "batched report item %r failed: %s", item, e
                    )
                    ok = False
            return ok
        if isinstance(request, msg.DatasetShardParams):
            self._task_manager.new_dataset(request)
            return True
        if isinstance(request, msg.ShardCheckpoint):
            return self._task_manager.restore_dataset_from_checkpoint(
                request
            )
        if isinstance(request, msg.TaskResult):
            return self._task_manager.report_task_status(
                request.dataset_name,
                request.task_id,
                success=not request.err_message,
            )
        if isinstance(request, msg.ResourceStats):
            if self._job_manager:
                self._job_manager.update_node_resource_usage(
                    node_type,
                    node_id,
                    request.cpu_percent,
                    request.memory_mb,
                    request.tpu_stats,
                )
            if self._health_engine is not None:
                self._health_engine.observe_resource(
                    node_id, request.cpu_percent, request.memory_mb
                )
            return True
        if isinstance(request, msg.GlobalStep):
            if self._speed_monitor:
                self._speed_monitor.collect_global_step(
                    request.step, request.timestamp or time.time()
                )
            if self._health_engine is not None:
                self._health_engine.observe_step(
                    node_id,
                    request.step,
                    request.timestamp or time.time(),
                )
            return True
        if isinstance(request, msg.NodeAddress):
            if self._job_manager:
                self._job_manager.update_node_address(
                    request.node_type, request.node_id, request.addr
                )
            return True
        if isinstance(request, msg.NodeTopology):
            manager = self._rdzv_managers.get(
                RendezvousName.ELASTIC_TRAINING
            )
            if manager is not None and hasattr(
                manager, "set_node_topology"
            ):
                manager.set_node_topology(
                    request.node_rank, tuple(request.levels)
                )
            return True
        if isinstance(request, msg.NetworkStatus):
            manager = self._rdzv_managers.get(
                RendezvousName.NETWORK_CHECK
            )
            if manager:
                manager.report_network_status(
                    request.node_rank,
                    request.succeeded,
                    request.elapsed_time,
                )
            return True
        if isinstance(request, msg.NodeEventMessage):
            return True
        if isinstance(request, msg.NodeFailure):
            if self._job_manager:
                self._job_manager.handle_training_failure(
                    node_type,
                    node_id,
                    request.restart_count,
                    request.error_data,
                    request.level,
                )
            if request.level == TrainingExceptionLevel.NODE_PREEMPTED:
                # graceful drain done on the node: fence it out of the
                # next round NOW so survivors' waiting-count long-polls
                # wake within one monitor interval (waiting for its
                # heartbeat to go stale would eat the preemption lead)
                training = self._rdzv_managers.get(
                    RendezvousName.ELASTIC_TRAINING
                )
                if training is not None:
                    training.fence_node(node_id)
            if self._health_engine is not None:
                self._health_engine.observe_fault(
                    node_id, request.level
                )
            return True
        if isinstance(request, msg.RendezvousParams):
            for manager in self._rdzv_managers.values():
                manager.update_rdzv_params(
                    request.min_nodes,
                    request.max_nodes,
                    request.waiting_timeout,
                    request.node_unit,
                )
            return True
        if isinstance(request, msg.KeyValuePair):
            self._kv_store.set(request.key, request.value)
            return True
        if isinstance(request, msg.ParallelConfig):
            if self._job_manager:
                self._job_manager.update_paral_config(request)
            return True
        if isinstance(request, msg.HeartBeat):
            if self._job_manager:
                self._job_manager.collect_node_heartbeat(
                    node_type, node_id, request.timestamp or time.time()
                )
            if self._health_engine is not None:
                self._health_engine.observe_heartbeat(
                    node_id, request.timestamp or time.time()
                )
            return True
        if isinstance(request, msg.NodeCheckpointState):
            manager = self._rdzv_managers.get(
                RendezvousName.ELASTIC_TRAINING
            )
            if manager:
                return manager.sync_ckpt_nodes(node_id, request.step)
            return False
        if isinstance(request, msg.ModelInfo):
            return True
        if isinstance(request, msg.DiagnosisReportData):
            if self._diagnosis_manager:
                from dlrover_tpu.master.diagnosis import DiagnosisData

                self._diagnosis_manager.collect_data(
                    DiagnosisData(
                        data_type=request.data_cls,
                        content=request.data_content,
                        node_rank=request.node_rank,
                    )
                )
            return True
        if isinstance(request, msg.ProfileReport):
            if self._capture is not None:
                self._capture.record_result(
                    request.node_rank
                    if request.node_rank >= 0
                    else node_id,
                    summary=request.summary,
                    artifact=request.artifact,
                    reason=request.reason,
                    capture_id=getattr(request, "capture_id", 0),
                )
                return True
            # profiler kill-switched on the master: drop with a trace
            # (an old agent answering a pre-switch directive)
            logger.warning(
                "profile report from node %s dropped: no capture "
                "coordinator", node_id,
            )
            return False
        if isinstance(request, msg.TimelineEventsReport):
            if self._timeline_aggregator is not None:
                self._timeline_aggregator.add_events(
                    node_id, request.events
                )
            return True
        if isinstance(request, msg.Event):
            logger.info(
                "event from %s-%s: %s %s %s",
                node_type, node_id,
                request.event_type, request.action, request.msg,
            )
            return True
        if isinstance(request, (msg.SyncJoin, msg.SyncFinish,
                                msg.SyncBarrier)):
            if self._sync_service:
                return self._sync_service.handle(node_type, node_id,
                                                 request)
            return True
        if isinstance(request, msg.PsReady):
            return True
        if isinstance(request, msg.SucceededRequest):
            return True
        logger.warning("unhandled report: %r", request)
        return False


def create_master_service(port: int, servicer: MasterServicer,
                          max_workers: int = 0):
    """Build the gRPC server wired to the servicer.  ``max_workers``
    0 resolves ``DLROVER_TPU_MASTER_WORKERS`` (default 64) — each
    parked long-poll holds one of these threads for its whole wait,
    so the fan-in ceiling must be raisable without a code change."""
    return build_master_server(
        port,
        servicer.report,
        servicer.get,
        max_workers=max_workers or master_workers(),
    )
