"""Auto-scalers: the periodic decide-and-act loops.

Two generations live here:

- :class:`AllreduceAutoScaler` — the seed loop (reference parity:
  ``dlrover/python/master/node/job_auto_scaler.py`` —
  ``AllreduceTrainingAutoScaler:271``): poll the ``SpeedMonitor``,
  ask the :class:`LocalAllreduceOptimizer` for a plan, execute it
  through ``Scaler.scale``.  This is what ``DLROVER_TPU_BRAIN=0``
  pins, byte-for-byte in decision behavior.
- :class:`BrainAutoScaler` — the observatory-fed autonomy loop
  (ROADMAP item 1; PAPER.md §1's Brain/ResourceOptimizer claim): each
  cycle assembles :class:`ObservatorySignals` from the PR-8
  ``HealthEngine`` + the goodput ledger + the live rendezvous world,
  asks :class:`ObservatoryBrainOptimizer` for at most one
  :class:`BrainDecision`, and executes it as ONE planned action
  through :class:`~dlrover_tpu.master.brain.BrainExecutor`.  Every
  decision and execution outcome is journaled (the PR-7
  ``ControlPlaneJournal`` ``brain`` component) and emitted on the
  timeline (``scale_decision`` / ``scale_execute`` instants,
  ``dlrover_tpu_autoscale_*`` metrics), so a master failover
  mid-action resumes or safely abandons it instead of flip-flopping.
"""

import threading
import time
from typing import Callable, Optional

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.master.resource_optimizer import (
    BrainDecision,
    JobStage,
    LocalAllreduceOptimizer,
    ObservatoryBrainOptimizer,
    ObservatorySignals,
    OUTCOME_DONE,
)
from dlrover_tpu.master.scaler import Scaler


def _registry():
    from dlrover_tpu.observability.metrics import get_registry

    return get_registry()



class _DecisionLoop:
    """Shared thread/lifecycle/error machinery for both scaler
    generations: a daemon loop ticking every ``interval``, failure
    accounting into ``dlrover_tpu_autoscale_errors`` with a throttled
    traceback, and a stop() that JOINS so master shutdown can't leak
    a mid-decision cycle.  Subclasses implement ``_cycle()``."""

    #: a failing cycle's traceback is logged at most once per this
    #: window (the counter still ticks every failure) — a wedged
    #: dependency must not write an identical stack trace every
    #: interval forever
    ERROR_LOG_COOLDOWN_S = 300.0
    _THREAD_NAME = "auto-scaler"
    _LOG_PREFIX = "auto-scale cycle"

    def __init__(self, interval: float):
        self._interval = interval
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.cycle_errors = 0
        self._last_error_log = 0.0

    def _cycle(self):
        raise NotImplementedError

    def start(self):
        # is_alive guard: a stop() whose join timed out on a wedged
        # cycle keeps _thread set; once that thread finally exits a
        # later start() must still work, not no-op forever
        if self._thread is not None and self._thread.is_alive():
            return
        self._stopped.clear()
        self._thread = threading.Thread(
            target=self._loop, name=self._THREAD_NAME, daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 5.0):
        """Signal the loop and JOIN it — master shutdown must not
        leak a mid-decision cycle into the dying process."""
        self._stopped.set()
        thread = self._thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=timeout)
            if thread.is_alive():
                logger.warning(
                    "%s thread did not stop within %.1fs",
                    self._THREAD_NAME, timeout,
                )
            else:
                self._thread = None

    def _loop(self):
        while not self._stopped.wait(self._interval):
            try:
                self._cycle()
            except Exception as e:  # noqa: BLE001
                self._on_cycle_error(e)

    def _on_cycle_error(self, e: BaseException):
        """Count every failure in the metric, but write the full
        traceback at most once per cooldown — repeated identical
        warnings forever were worse than silence."""
        self.cycle_errors += 1
        try:
            _registry().inc_counter("dlrover_tpu_autoscale_errors")
        except Exception:  # noqa: BLE001 - accounting must not throw
            pass
        now = time.monotonic()
        if now - self._last_error_log >= self.ERROR_LOG_COOLDOWN_S:
            self._last_error_log = now
            logger.warning(
                "%s failed (%d so far): %s",
                self._LOG_PREFIX, self.cycle_errors, e, exc_info=True,
            )
        else:
            logger.warning("%s failed: %s", self._LOG_PREFIX, e)


class AllreduceAutoScaler(_DecisionLoop):
    def __init__(
        self,
        optimizer: LocalAllreduceOptimizer,
        scaler: Scaler,
        speed_monitor=None,
        job_manager=None,
        rendezvous_manager=None,
        interval: float = 60.0,
    ):
        self._optimizer = optimizer
        self._scaler = scaler
        self._speed_monitor = speed_monitor
        self._job_manager = job_manager
        self._rdzv_manager = rendezvous_manager
        super().__init__(interval)
        self._started_job = False

    def execute_initial_plan(self):
        plan = self._optimizer.generate_plan(JobStage.CREATE)
        if plan and not plan.is_empty():
            self._scaler.scale(plan)
            self._started_job = True

    def _collect_speed(self):
        if self._speed_monitor is None:
            return
        # running_speed is a METHOD — the bare attribute compared >0
        # raised TypeError every cycle, silently eaten by the loop's
        # catch-all (caught by the autoscale e2e test)
        speed = self._speed_monitor.running_speed()
        worker_num = 0
        if self._job_manager is not None:
            worker_num = len(self._job_manager.get_running_nodes())
        if worker_num > 0:
            # the optimizer's settle decision needs the ACTUAL world
            # size even when no fresh speed sample exists this cycle
            self._optimizer.set_current_workers(worker_num)
        if speed > 0 and worker_num > 0:
            self._optimizer.record_speed(worker_num, speed)

    def _collect_stragglers(self):
        """Feed the health-check rounds' straggler verdict to the
        straggler-migrate algorithm.  Ranks are mapped to node NAMES
        (the scaler removes pods by name; an unmapped rank is skipped
        rather than producing an un-executable plan)."""
        if self._rdzv_manager is None:
            return
        try:
            stragglers, _ = self._rdzv_manager.check_straggler()
        except Exception:  # noqa: BLE001
            return
        if not stragglers:
            return
        names = []
        rank_to_name = {}
        if self._job_manager is not None:
            for node in self._job_manager.get_running_nodes():
                key = (
                    node.rank_index
                    if node.rank_index is not None
                    else node.id
                )
                if node.name:
                    rank_to_name[key] = node.name
        for rank in stragglers:
            name = rank_to_name.get(rank)
            if name:
                names.append(name)
            else:
                logger.warning(
                    "straggler rank %s has no known node name; "
                    "skipping migration", rank,
                )
        if names:
            self._optimizer.report_stragglers(names)

    def _cycle(self):
        self._collect_speed()
        self._collect_stragglers()
        plan = self._optimizer.generate_plan(JobStage.RUNNING)
        if plan and not plan.is_empty():
            logger.info("auto-scaler executing plan: %s", plan)
            self._scaler.scale(plan)


class BrainAutoScaler(_DecisionLoop):
    """The closed autonomy loop: observe (health engine + ledger) →
    decide (:class:`ObservatoryBrainOptimizer`) → act
    (:class:`BrainExecutor`) → verify, with everything journaled.

    Implements the journal-component contract
    (``set_journal`` / ``export_state`` / ``restore_state``) so the
    optimizer's hysteresis/cooldown state and any in-flight action
    survive a master failover under the PR-7 ``ControlPlaneJournal``.
    """

    _THREAD_NAME = "brain-auto-scaler"
    _LOG_PREFIX = "brain cycle"

    def __init__(
        self,
        optimizer: ObservatoryBrainOptimizer,
        executor,
        health_engine=None,
        timeline_aggregator=None,
        interval: Optional[float] = None,
        job: str = "default",
    ):
        from dlrover_tpu.common.env import brain_interval_s
        from dlrover_tpu.master.brain import execution_deadline_s

        self._optimizer = optimizer
        self._executor = executor
        self._health = health_engine
        self._aggregator = timeline_aggregator
        super().__init__(
            brain_interval_s() if interval is None else interval
        )
        self._deadline_s = execution_deadline_s(self._interval)
        self._job = job
        self._journal_cb: Optional[Callable[[str, dict], None]] = None
        #: an in-flight decision inherited from a dead incarnation
        #: must be re-armed (its directive died with the old master)
        self._resume_pending = False

    @property
    def directives(self):
        return self._executor.directives

    @property
    def optimizer(self) -> ObservatoryBrainOptimizer:
        return self._optimizer

    @property
    def executor(self):
        return self._executor

    def set_scaler(self, scaler):
        self._executor.set_scaler(scaler)

    # ------------------------------------------------------------ loop
    def _cycle(self):
        self.run_cycle()

    # ----------------------------------------------------------- signals
    def gather_signals(self, now: Optional[float] = None) -> ObservatorySignals:
        world = self._executor.current_world()
        min_nodes, max_nodes = self._executor.world_bounds()
        signals = ObservatorySignals(
            world=world,
            min_nodes=min_nodes,
            max_nodes=max_nodes,
            fenced=self._executor.fenced(),
            can_launch=self._executor.can_launch,
            now=now or time.time(),
        )
        if self._health is not None:
            signals.stragglers = self._health.stragglers()
            signals.hangs = self._health.hang_suspects()
            signals.stall_shares = self._health.stall_shares()
            signals.median_step_time_s = (
                self._health.median_step_time()
            )
        if self._aggregator is not None:
            try:
                signals.goodput = float(
                    self._aggregator.ledger().get("goodput", 0.0)
                )
            except Exception as e:  # noqa: BLE001 - advisory context
                logger.warning("brain ledger read failed: %s", e)
        return signals

    # ------------------------------------------------------------ cycle
    def run_cycle(self, now: Optional[float] = None):
        """One decide/verify beat (public so tests and harnesses can
        drive the loop synchronously)."""
        now = now or time.time()
        in_flight = self._optimizer.in_flight
        if in_flight is not None:
            self._advance_in_flight(in_flight, now)
            return
        signals = self.gather_signals(now)
        decision = self._optimizer.decide(signals)
        self._export_world_gauge(signals)
        if decision is None:
            return
        logger.info(
            "brain decision %d: %s node=%s (%s) world %d -> %d",
            decision.decision_id, decision.action, decision.node,
            decision.reason, decision.from_world, decision.to_world,
        )
        self._journal()
        self._emit_decision(decision)
        self._executor.begin(decision)

    def _advance_in_flight(self, decision: BrainDecision, now: float):
        if self._resume_pending:
            # inherited from a dead incarnation: its directive died
            # with the old master's memory — re-arm (or observe that
            # the world already reflects it)
            self._resume_pending = False
            if not self._executor.resume(decision):
                self._finish(decision, OUTCOME_DONE, now)
                return
            logger.info(
                "brain: resumed in-flight decision %d (%s node=%s) "
                "after failover",
                decision.decision_id, decision.action, decision.node,
            )
        outcome = self._executor.check(decision)
        if outcome is None and now - decision.made_at >= self._deadline_s:
            outcome = self._executor.force(decision)
        if outcome is not None:
            self._finish(decision, outcome, now)

    def _finish(self, decision: BrainDecision, outcome: str, now: float):
        logger.info(
            "brain decision %d executed: %s (%s)",
            decision.decision_id, outcome, decision.action,
        )
        self._optimizer.complete(outcome, now=now)
        self._journal()
        self._emit_execute(decision, outcome)

    # --------------------------------------------------------- telemetry
    def _emit_decision(self, decision: BrainDecision):
        from dlrover_tpu.observability.events import get_event_logger

        get_event_logger().instant(
            "scale_decision",
            action=decision.action,
            reason=decision.reason,
            from_world=decision.from_world,
            to_world=decision.to_world,
            plane="train",
            target_node=decision.node,
            decision_id=decision.decision_id,
        )
        try:
            _registry().inc_counter(
                "dlrover_tpu_autoscale_decisions",
                labels={"action": decision.action},
            )
        except Exception:  # noqa: BLE001
            pass

    def _emit_execute(self, decision: BrainDecision, outcome: str):
        from dlrover_tpu.observability.events import get_event_logger

        get_event_logger().instant(
            "scale_execute",
            action=decision.action,
            reason=decision.reason,
            from_world=decision.from_world,
            to_world=decision.to_world,
            plane="train",
            target_node=decision.node,
            decision_id=decision.decision_id,
            outcome=outcome,
        )
        try:
            _registry().inc_counter(
                "dlrover_tpu_autoscale_executions",
                labels={"action": decision.action,
                        "outcome": outcome},
            )
        except Exception:  # noqa: BLE001
            pass

    def _export_world_gauge(self, signals: ObservatorySignals):
        try:
            _registry().set_gauge(
                "dlrover_tpu_autoscale_world", len(signals.world)
            )
        except Exception:  # noqa: BLE001
            pass

    # -------------------------------------------------- journal contract
    def set_journal(self, cb: Optional[Callable[[str, dict], None]]):
        self._journal_cb = cb

    def _journal(self):
        if self._journal_cb is None:
            return
        try:
            self._journal_cb("state", self.export_state())
        except Exception as e:  # noqa: BLE001
            logger.warning("brain journal failed: %s", e)

    def export_state(self) -> dict:
        return self._optimizer.export_state()

    def restore_state(self, state: dict):
        """Journal replay: reinstall the optimizer's hysteresis /
        cooldown / in-flight state.  A restored in-flight action is
        resumed (directive re-armed) or observed-as-done on the first
        cycle; its original decision deadline still bounds it, so a
        long outage abandons instead of acting on stale evidence."""
        self._optimizer.restore_state(state)
        self._resume_pending = self._optimizer.in_flight is not None

    def status(self) -> dict:
        """The Brain's corner of the JobStatus snapshot."""
        last = self._optimizer.last_decision
        in_flight = self._optimizer.in_flight
        return {
            "interval_s": self._interval,
            "cycle_errors": self.cycle_errors,
            "last_decision": last.to_dict() if last else None,
            "in_flight": in_flight.to_dict() if in_flight else None,
            "pending_directives": (
                self._executor.directives.pending_nodes()
            ),
        }
