"""JobAutoScaler: periodic optimizer-driven scaling.

Reference parity: ``dlrover/python/master/node/job_auto_scaler.py`` —
``AllreduceTrainingAutoScaler:271`` (periodically query the resource
optimizer, execute plans through the scaler) and the factory ``:40``.
The PS variant is out of TPU scope (SURVEY.md §2.8 last row).
"""

import threading
from typing import Optional

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.master.resource_optimizer import (
    JobStage,
    LocalAllreduceOptimizer,
)
from dlrover_tpu.master.scaler import Scaler


class AllreduceAutoScaler:
    def __init__(
        self,
        optimizer: LocalAllreduceOptimizer,
        scaler: Scaler,
        speed_monitor=None,
        job_manager=None,
        rendezvous_manager=None,
        interval: float = 60.0,
    ):
        self._optimizer = optimizer
        self._scaler = scaler
        self._speed_monitor = speed_monitor
        self._job_manager = job_manager
        self._rdzv_manager = rendezvous_manager
        self._interval = interval
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started_job = False

    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="auto-scaler", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stopped.set()

    def execute_initial_plan(self):
        plan = self._optimizer.generate_plan(JobStage.CREATE)
        if plan and not plan.is_empty():
            self._scaler.scale(plan)
            self._started_job = True

    def _collect_speed(self):
        if self._speed_monitor is None:
            return
        # running_speed is a METHOD — the bare attribute compared >0
        # raised TypeError every cycle, silently eaten by the loop's
        # catch-all (caught by the autoscale e2e test)
        speed = self._speed_monitor.running_speed()
        worker_num = 0
        if self._job_manager is not None:
            worker_num = len(self._job_manager.get_running_nodes())
        if worker_num > 0:
            # the optimizer's settle decision needs the ACTUAL world
            # size even when no fresh speed sample exists this cycle
            self._optimizer.set_current_workers(worker_num)
        if speed > 0 and worker_num > 0:
            self._optimizer.record_speed(worker_num, speed)

    def _collect_stragglers(self):
        """Feed the health-check rounds' straggler verdict to the
        straggler-migrate algorithm.  Ranks are mapped to node NAMES
        (the scaler removes pods by name; an unmapped rank is skipped
        rather than producing an un-executable plan)."""
        if self._rdzv_manager is None:
            return
        try:
            stragglers, _ = self._rdzv_manager.check_straggler()
        except Exception:  # noqa: BLE001
            return
        if not stragglers:
            return
        names = []
        rank_to_name = {}
        if self._job_manager is not None:
            for node in self._job_manager.get_running_nodes():
                key = (
                    node.rank_index
                    if node.rank_index is not None
                    else node.id
                )
                if node.name:
                    rank_to_name[key] = node.name
        for rank in stragglers:
            name = rank_to_name.get(rank)
            if name:
                names.append(name)
            else:
                logger.warning(
                    "straggler rank %s has no known node name; "
                    "skipping migration", rank,
                )
        if names:
            self._optimizer.report_stragglers(names)

    def _loop(self):
        while not self._stopped.wait(self._interval):
            try:
                self._collect_speed()
                self._collect_stragglers()
                plan = self._optimizer.generate_plan(JobStage.RUNNING)
                if plan and not plan.is_empty():
                    logger.info("auto-scaler executing plan: %s", plan)
                    self._scaler.scale(plan)
            except Exception as e:  # noqa: BLE001
                logger.warning("auto-scale cycle failed: %s", e)
