"""Job masters: local (in-process, spawned by ``dlrover-tpu-run``) and
distributed (its own process/pod supervising a multi-host job).

Reference parity: ``dlrover/python/master/local_master.py`` and
``dist_master.py:86,175,211``.
"""

import threading
import time
from typing import Optional

from dlrover_tpu.common.constants import (
    JobExitReason,
    RendezvousName,
)
from dlrover_tpu.common.global_context import Context
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.master.job_manager import (
    AllReduceNodeHandlingCallback,
    DistributedJobManager,
    LocalJobManager,
    TaskRescheduleCallback,
)
from dlrover_tpu.master.kv_store import KVStoreService
from dlrover_tpu.master.rendezvous import (
    ElasticTrainingRendezvousManager,
    NetworkCheckRendezvousManager,
)
from dlrover_tpu.master.servicer import (
    MasterServicer,
    create_master_service,
)
from dlrover_tpu.master.shard.task_manager import TaskManager
from dlrover_tpu.master.speed_monitor import SpeedMonitor

_ctx = Context.singleton_instance()


class JobMaster:
    """Common wiring of the master components + gRPC service."""

    def __init__(self, port: int, node_num: int = 1,
                 job_manager=None, diagnosis_manager=None):
        import os

        from dlrover_tpu.common.env import (
            brain_enabled,
            master_workers,
            observatory_enabled,
            self_obs_enabled,
        )
        from dlrover_tpu.master.datastore import get_default_datastore
        from dlrover_tpu.observability.events import TimelineAggregator
        from dlrover_tpu.observability.metrics import get_registry

        self._job_name = os.getenv("DLROVER_TPU_JOB_NAME", "default")
        self.speed_monitor = SpeedMonitor()
        # the observatory: streaming per-node health derivations over
        # the incoming timeline batches + agent reports.  None under
        # the DLROVER_TPU_OBSERVATORY=0 kill-switch — every consumer
        # (diagnosis operators, JobStatusRequest, status server,
        # gauges) degrades to the pre-observatory behavior exactly.
        self.health_engine = None
        if observatory_enabled():
            from dlrover_tpu.observability.health import HealthEngine

            self.health_engine = HealthEngine(
                job=self._job_name, registry=get_registry()
            )
        # unified job-event timeline: per-node streams merge here, the
        # goodput ledger is served live (get-RPC + exporter gauges) and
        # durably (sqlite datastore when configured); the health
        # engine taps every accepted batch
        self.timeline_aggregator = TimelineAggregator(
            job=self._job_name,
            registry=get_registry(),
            datastore=get_default_datastore(),
            health=self.health_engine,
        )
        # the deep-capture arm (None = DLROVER_TPU_PROFILE=0 or
        # observatory off): diagnosis-triggered captures ride the
        # directive piggyback, results land in the Brain `profiles`
        # table and the JobStatus snapshot
        self.capture_coordinator = None
        if self.health_engine is not None:
            from dlrover_tpu.common.env import profile_enabled

            if profile_enabled():
                from dlrover_tpu.master.capture import (
                    CaptureCoordinator,
                )

                self.capture_coordinator = CaptureCoordinator(
                    job=self._job_name,
                    datastore=get_default_datastore(),
                )
        self.task_manager = TaskManager(speed_monitor=self.speed_monitor)
        self.rdzv_managers = {
            RendezvousName.ELASTIC_TRAINING:
                ElasticTrainingRendezvousManager(),
            RendezvousName.NETWORK_CHECK: NetworkCheckRendezvousManager(),
        }
        self.kv_store = KVStoreService()
        self.job_manager = job_manager
        # control-plane SELF-telemetry: the master watching itself
        # (per-RPC-kind latency histograms, pool occupancy, state
        # growth, journal lag) + the MasterHealth overload deriver.
        # None under DLROVER_TPU_SELF_OBS=0 — the pre-self-obs metric
        # surface exactly (pinned by tests).
        self.master_telemetry = None
        self.master_health = None
        if self_obs_enabled():
            from dlrover_tpu.observability.health import MasterHealth
            from dlrover_tpu.observability.self_telemetry import (
                MasterSelfTelemetry,
            )

            self.master_telemetry = MasterSelfTelemetry(
                registry=get_registry(),
                pool_size=master_workers(),
            )
            self.master_telemetry.attach(
                kv_store=self.kv_store,
                rdzv_managers=self.rdzv_managers,
                task_manager=self.task_manager,
                timeline_aggregator=self.timeline_aggregator,
                datastore=get_default_datastore(),
            )
            self.master_health = MasterHealth(self.master_telemetry)
        if diagnosis_manager is None:
            from dlrover_tpu.master.diagnosis import DiagnosisManager

            # with the observatory on, the chain sits on top of the
            # streaming derivations (straggler / data-stall / hang
            # watchdog operators) and records conclusions to the
            # timeline + Brain; off, it is exactly the old manager
            diagnosis_manager = DiagnosisManager(
                speed_monitor=self.speed_monitor,
                health_engine=self.health_engine,
                datastore=get_default_datastore(),
                job=self._job_name,
                capture=self.capture_coordinator,
                master_health=self.master_health,
            )
        self.diagnosis_manager = diagnosis_manager
        # the autonomy loop (ROADMAP item 1): observatory signals ->
        # hysteresis-guarded BrainDecision -> ONE planned action
        # (cooperative drain directive + fence + reshard re-mesh, or
        # a scaler plan).  None under DLROVER_TPU_BRAIN=0 or with the
        # observatory off — the seed AllreduceAutoScaler (distributed
        # masters with a scaler) is then the only scaling loop,
        # exactly as before.
        self.brain = None
        if brain_enabled() and self.health_engine is not None:
            from dlrover_tpu.master.auto_scaler import BrainAutoScaler
            from dlrover_tpu.master.brain import (
                BrainExecutor,
                NodeDirectives,
            )
            from dlrover_tpu.master.resource_optimizer import (
                ObservatoryBrainOptimizer,
            )

            self.brain = BrainAutoScaler(
                ObservatoryBrainOptimizer(),
                BrainExecutor(
                    rdzv_manager=self.rdzv_managers[
                        RendezvousName.ELASTIC_TRAINING
                    ],
                    directives=NodeDirectives(),
                    job_manager=self.job_manager,
                ),
                health_engine=self.health_engine,
                timeline_aggregator=self.timeline_aggregator,
                job=self._job_name,
            )
        #: plain-HTTP /metrics + /status (off unless --status_port)
        self.status_server = None
        self.speed_monitor.set_target_worker_num(node_num)
        self._node_num = node_num
        self._port = port
        self._server = None
        self._exit_reason: Optional[str] = None
        self._stopped = threading.Event()
        #: fencing identity (durable when a Brain db is configured;
        #: epoch 0 / incarnation 0 = no durability, fencing inert)
        self.job_epoch = 0
        self.incarnation = 0
        #: durable control-plane journal (None = failover disabled or
        #: no Brain db — today's memory-only behavior exactly)
        self.control_journal = None

        self.job_manager.add_node_event_callback(
            TaskRescheduleCallback(self.task_manager)
        )
        self.job_manager.add_node_event_callback(
            AllReduceNodeHandlingCallback(self)
        )

    @property
    def port(self) -> int:
        return self._port

    @property
    def addr(self) -> str:
        return f"127.0.0.1:{self._port}"

    def _setup_failover(self):
        """Durable control-plane state: registers this incarnation,
        replays snapshot+journal into the components, then attaches
        the journal hooks — all BEFORE the gRPC server opens, so the
        first reconnecting agent sees the resumed state."""
        from dlrover_tpu.common.env import master_failover_enabled
        from dlrover_tpu.master.datastore import get_default_datastore

        if not master_failover_enabled():
            return
        store = get_default_datastore()
        if store is None:
            return
        from dlrover_tpu.master.failover import ControlPlaneJournal
        from dlrover_tpu.observability.events import get_event_logger

        self.job_epoch, self.incarnation = store.bump_incarnation(
            self._job_name
        )
        self.control_journal = ControlPlaneJournal(
            store,
            self._job_name,
            kv_store=self.kv_store,
            rdzv_managers=self.rdzv_managers,
            task_manager=self.task_manager,
            job_manager=self.job_manager,
            brain=self.brain,
            capture=self.capture_coordinator,
        )
        stats = self.control_journal.recover()
        self.control_journal.attach()
        self.control_journal.start()
        if self.incarnation > 1:
            get_event_logger().instant(
                "master_restart",
                incarnation=self.incarnation,
                job_epoch=self.job_epoch,
                **stats,
            )
            logger.info(
                "master incarnation %s resumed job epoch %s (%s)",
                self.incarnation, self.job_epoch, stats,
            )

    def prepare(self):
        self._setup_failover()
        if (
            self.master_telemetry is not None
            and self.control_journal is not None
        ):
            # the journal only exists once failover setup ran; its
            # snapshot age/duration joins the self-telemetry sweep
            self.master_telemetry.attach(
                journal=self.control_journal
            )
        servicer = MasterServicer(
            task_manager=self.task_manager,
            job_manager=self.job_manager,
            speed_monitor=self.speed_monitor,
            rdzv_managers=self.rdzv_managers,
            kv_store=self.kv_store,
            diagnosis_manager=self.diagnosis_manager,
            timeline_aggregator=self.timeline_aggregator,
            health_engine=self.health_engine,
            brain=self.brain,
            capture_coordinator=self.capture_coordinator,
            job_epoch=self.job_epoch,
            incarnation=self.incarnation,
            telemetry=self.master_telemetry,
        )
        self._servicer = servicer
        self._server = create_master_service(self._port, servicer)
        self._server.start()
        self.task_manager.start()
        self.job_manager.start()
        if self.diagnosis_manager:
            self.diagnosis_manager.start()
        if self.brain is not None:
            self.brain.start()
        self._start_status_server(servicer)
        logger.info("master serving on port %s", self._port)

    def _start_status_server(self, servicer):
        """Plain-HTTP ``/metrics`` (Prometheus text) + ``/status``
        (the JobStatusRequest snapshot as JSON).  Off by default:
        needs ``--status_port`` (``DLROVER_TPU_STATUS_PORT``) AND the
        observatory on."""
        import os

        raw = os.getenv("DLROVER_TPU_STATUS_PORT", "")
        if not raw:
            return
        try:
            port = int(raw)
        except ValueError:
            logger.warning(
                "ignoring malformed DLROVER_TPU_STATUS_PORT=%r", raw
            )
            return
        if port < 0:
            return
        if self.health_engine is None:
            logger.info(
                "status port requested but observatory is off "
                "(DLROVER_TPU_OBSERVATORY=0); not serving"
            )
            return
        from dlrover_tpu.common import messages as msg
        from dlrover_tpu.observability.metrics import get_registry
        from dlrover_tpu.observability.status_server import (
            StatusServer,
        )

        def _snapshot():
            res = servicer._job_status(msg.JobStatusRequest())
            return res.status if res.available else {}

        self.status_server = StatusServer(
            port,
            registry=get_registry(),
            snapshot_fn=_snapshot,
            health_engine=self.health_engine,
            telemetry=self.master_telemetry,
        )
        try:
            self.status_server.start()
        except OSError as e:
            logger.warning(
                "status server failed to bind :%d: %s", port, e
            )
            self.status_server = None

    def process_diagnosis(self):
        """Feed inference-chain conclusions to the job manager (run
        from the supervision loops)."""
        if not self.diagnosis_manager:
            return
        conclusions = self.diagnosis_manager.take_conclusions()
        if conclusions:
            self.job_manager.apply_diagnosis_conclusions(conclusions)

    def stop(self, reason: str = ""):
        self._exit_reason = reason or self._exit_reason
        self._stopped.set()
        if self.control_journal is not None:
            # a job-terminal stop (request_stop always passes a
            # JobExitReason) RETIRES the durable state — a later run
            # under the same Brain db + job name must not inherit this
            # job's exhausted datasets / stale KV keys; a bare stop()
            # (master-only shutdown) snapshots so the next incarnation
            # resumes
            self.control_journal.stop(
                retire=bool(self._exit_reason)
            )
        self.task_manager.stop()
        self.job_manager.stop()
        if self.diagnosis_manager:
            self.diagnosis_manager.stop()
        if self.brain is not None:
            self.brain.stop()
        if self.status_server is not None:
            self.status_server.stop()
            self.status_server = None
        if self._server:
            self._server.stop(grace=0.5)

    def request_stop(self, success: bool, reason: str, msg: str = ""):
        logger.info("stop requested: success=%s reason=%s %s",
                    success, reason, msg)
        self.stop(reason)


class LocalJobMaster(JobMaster):
    """In-process master for single-host runs (reference:
    ``local_master.py:118``)."""

    def __init__(self, port: int, node_num: int = 1):
        super().__init__(
            port, node_num, job_manager=LocalJobManager(node_num)
        )

    def run(self):
        """Block until training finishes (used when run as a thread)."""
        while not self._stopped.is_set():
            if self.task_manager.finished():
                logger.info("all dataset tasks finished")
                self.request_stop(True, JobExitReason.SUCCEEDED)
                break
            self.process_diagnosis()
            time.sleep(1)
        return 0


class DistributedJobMaster(JobMaster):
    """Multi-host master with a 30s supervision loop deciding
    early-stop / hang / all-exited (reference: ``dist_master.py:211``)."""

    SUPERVISE_INTERVAL = 30

    def __init__(self, port: int, node_num: int, scaler=None,
                 diagnosis_manager=None, pending_timeout=None,
                 autoscale: bool = True, max_workers: int = 0):
        super().__init__(
            port,
            node_num,
            job_manager=DistributedJobManager(
                node_num, scaler=scaler, pending_timeout=pending_timeout
            ),
            diagnosis_manager=diagnosis_manager,
        )
        if not autoscale and self.brain is not None:
            # autoscaling explicitly disabled: the Brain must not run
            # either (dropped before prepare() wires the journal /
            # servicer, so nothing references it)
            self.brain = None
        if self.brain is not None and scaler is not None:
            # the Brain gains launch capacity: grow decisions and
            # drain REPLACEMENTS execute through the same scaler the
            # job manager relaunches with
            self.brain.set_scaler(scaler)
        # seed periodic optimize -> ScalePlan cycle (reference
        # job_auto_scaler.py:271); with the Brain on it is replaced
        # wholesale — DLROVER_TPU_BRAIN=0 reproduces it exactly.  The
        # plan executes through the SAME scaler the job manager
        # relaunches with, so a no-op scaler (local runs) makes this
        # a cheap observer.
        self.auto_scaler = None
        if autoscale and scaler is not None and self.brain is None:
            import os

            from dlrover_tpu.master.auto_scaler import (
                AllreduceAutoScaler,
            )
            from dlrover_tpu.master.resource_optimizer import (
                LocalAllreduceOptimizer,
            )

            self.auto_scaler = AllreduceAutoScaler(
                LocalAllreduceOptimizer(
                    min_workers=node_num,
                    max_workers=max_workers or node_num,
                    job_name=os.getenv(
                        "DLROVER_TPU_JOB_NAME", "default"
                    ),
                ),
                scaler,
                speed_monitor=self.speed_monitor,
                job_manager=self.job_manager,
                rendezvous_manager=self.rdzv_managers.get(
                    RendezvousName.NETWORK_CHECK
                ),
            )

    def run(self) -> int:
        exit_code = 0
        if self.auto_scaler is not None:
            self.auto_scaler.start()
        while not self._stopped.is_set():
            if self.job_manager.all_workers_exited():
                if self.job_manager.all_workers_failed():
                    self.request_stop(
                        False, JobExitReason.WORKER_ERROR
                    )
                    exit_code = 1
                else:
                    self.request_stop(True, JobExitReason.SUCCEEDED)
                break
            stop_reason = self.job_manager.should_stop_job()
            if stop_reason:
                logger.error("stopping job: %s", stop_reason)
                self.request_stop(False, JobExitReason.WORKER_ERROR)
                exit_code = 1
                break
            if self.speed_monitor.step_is_stagnant():
                logger.warning("global step stagnant: possible hang")
                self.request_stop(False, JobExitReason.HANG_ERROR)
                exit_code = 1
                break
            if self.task_manager.finished():
                self.request_stop(True, JobExitReason.SUCCEEDED)
                break
            self.process_diagnosis()
            self._stopped.wait(self.SUPERVISE_INTERVAL)
        if self.auto_scaler is not None:
            self.auto_scaler.stop()
        return exit_code


def run_local_master(port: int, node_num: int) -> LocalJobMaster:
    """Start a local master on ``port`` in background threads and return
    it (what the run CLI calls on rank 0)."""
    master = LocalJobMaster(port, node_num)
    master.prepare()
    threading.Thread(
        target=master.run, name="local-master", daemon=True
    ).start()
    return master
