"""Master-side dynamic data sharding service.

Reference parity: ``dlrover/python/master/shard/task_manager.py:37,94,
126,169`` — dispatches shards to workers on demand, recovers shards of
dead workers, re-queues timed-out shards via a watcher thread, and
checkpoints/restores splitter + queue state.
"""

import dataclasses
import threading
import time
from typing import Callable, Dict, Optional

from dlrover_tpu.common.global_context import Context
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.messages import (
    DatasetShardParams,
    ShardCheckpoint,
    Task,
    TaskType,
)
from dlrover_tpu.master.shard.dataset_manager import BatchDatasetManager
from dlrover_tpu.master.shard.dataset_splitter import new_dataset_splitter

_ctx = Context.singleton_instance()


class TaskManager:
    #: long-poll wake slice: task availability is mostly event-driven
    #: (new dataset / task ack / recovery all notify) but the timeout
    #: watcher requeues on its own clock, so parked waiters re-check
    WAIT_SLICE_S = 0.5

    def __init__(self, worker_restart_timeout: float = 0.0,
                 speed_monitor=None, check_interval: float = 30.0):
        # a Condition IS a lock for ``with`` purposes; mutations that
        # can turn a WAIT answer into a real task notify long-pollers
        self._lock = threading.Condition()
        self._worker_restart_timeout = worker_restart_timeout
        self._datasets: Dict[str, BatchDatasetManager] = {}
        self._speed_monitor = speed_monitor
        self._task_timeout = _ctx.seconds_to_timeout_task
        self._check_interval = check_interval
        self._stopped = threading.Event()
        self._watcher: Optional[threading.Thread] = None
        self._worker_client_hosts: Dict[int, str] = {}
        #: creation params per dataset, kept so a restarted master can
        #: recreate the splitter before restoring its checkpoint
        self._dataset_params: Dict[str, DatasetShardParams] = {}
        #: failover journal hook: ``cb(op, args)``.  Full-state
        #: records ("dataset": splitter position + todo, with doing
        #: FOLDED INTO todo) go out only on the RARE mutations —
        #: creation, splitter refill, client checkpoint restore; a
        #: successful ack journals an O(1) "done" delta instead.
        #: Dispatches, failures, timeouts and dead-node recovery
        #: journal NOTHING: none of them change the durable view —
        #: an unjournaled lease is still in the durable todo, so
        #: replay re-queues it exactly like the timeout requeue path.
        #: (Journaling the full checkpoint per dispatch/ack was
        #: O(shards²) per epoch through the bounded write-behind
        #: queue, stalling the control plane under its own locks.)
        self._journal_cb: Optional[Callable[[str, dict], None]] = None

    def set_journal(self, cb: Optional[Callable[[str, dict], None]]):
        with self._lock:
            self._journal_cb = cb

    def _journal_dataset_locked(self, name: str):
        """Caller holds the lock."""
        if self._journal_cb is None:
            return
        dataset = self._datasets.get(name)
        params = self._dataset_params.get(name)
        if dataset is None or params is None:
            return
        try:
            self._journal_cb(
                "dataset",
                {
                    "name": name,
                    "params": dataclasses.asdict(params),
                    "ckpt": dataset.checkpoint(),
                },
            )
        except Exception as e:  # noqa: BLE001
            logger.warning("task journal failed: %s", e)

    def new_dataset(self, params: DatasetShardParams):
        with self._lock:
            if params.dataset_name in self._datasets:
                return
            shard_size = params.batch_size * params.num_minibatches_per_shard
            splitter = new_dataset_splitter(
                params.shuffle,
                shard_size,
                params.dataset_size,
                params.num_epochs,
                params.dataset_name,
                params.storage_type,
            )
            self._datasets[params.dataset_name] = BatchDatasetManager(
                params.task_type, params.batch_size, splitter
            )
            self._dataset_params[params.dataset_name] = params
            self._journal_dataset_locked(params.dataset_name)
            self._lock.notify_all()
            logger.info(
                "created dataset %s: size=%s shard=%s epochs=%s",
                params.dataset_name,
                params.dataset_size,
                shard_size,
                params.num_epochs,
            )

    def get_dataset(self, name: str) -> Optional[BatchDatasetManager]:
        return self._datasets.get(name)

    def get_task(self, node_id: int, dataset_name: str) -> Task:
        with self._lock:
            dataset = self._datasets.get(dataset_name)
            if dataset is None:
                return Task()
            refills = dataset.refill_count
            task = dataset.get_task(node_id)
            if dataset.refill_count != refills:
                # the splitter produced a new todo batch (epoch roll):
                # journal the full state — O(shards) once per epoch.
                # A plain dispatch journals nothing: the durable view
                # keeps the shard in todo, so a crash re-queues the
                # unacked lease exactly like the timeout path.
                self._journal_dataset_locked(dataset_name)
            return task

    def report_task_status(self, dataset_name: str, task_id: int,
                           success: bool):
        with self._lock:
            dataset = self._datasets.get(dataset_name)
            if dataset is None:
                return False
            ok, doing = dataset.report_task_status(task_id, success)
            if ok and success and doing is not None:
                # O(1) "done" delta: the shard left the system for
                # good.  A FAILED ack journals nothing — the shard
                # never left the durable todo (dispatches aren't
                # journaled), only its in-memory position moved.
                shard = doing.task.shard
                self._journal_delta_locked(
                    "done",
                    {
                        "name": dataset_name,
                        "shard": [shard.name, shard.start, shard.end],
                        "epoch": dataset.get_epoch(),
                        "step": dataset.completed_step,
                    },
                )
            # a failure requeues the shard and an ack can turn a
            # parked WAIT long-poller's answer into a real task
            self._lock.notify_all()
            return ok

    def _journal_delta_locked(self, op: str, args: dict):
        """Caller holds the lock."""
        if self._journal_cb is None:
            return
        try:
            self._journal_cb(op, args)
        except Exception as e:  # noqa: BLE001
            logger.warning("task journal failed: %s", e)

    def recover_tasks(self, node_id: int):
        """Recover all doing shards of a dead worker (reference ``:169``).

        Not journaled: the move is doing -> todo, and the durable view
        (which never saw the dispatch) already has the shard in todo."""
        with self._lock:
            for dataset in self._datasets.values():
                dataset.recover_tasks_of_node(node_id)
            self._lock.notify_all()

    def wait_task(self, node_id: int, dataset_name: str,
                  wait_timeout: float = 0.0) -> Task:
        """Long-poll ``get_task``: while the dataset would only hand
        out WAIT tasks, park on the condition (woken by acks/failures/
        recovery) up to ``wait_timeout`` — the WAIT answer then still
        goes out, so the client's loop semantics are unchanged."""
        deadline = time.monotonic() + max(wait_timeout, 0.0)
        while True:
            task = self.get_task(node_id, dataset_name)
            if task.task_type != TaskType.WAIT:
                return task
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return task
            with self._lock:
                self._lock.wait(min(remaining, self.WAIT_SLICE_S))

    def wait_training_started(self, wait_timeout: float = 0.0) -> bool:
        """Long-poll ``training_started``: block until the first
        dataset registration flips it (or the timeout elapses)."""
        deadline = time.monotonic() + max(wait_timeout, 0.0)
        with self._lock:
            while not self._datasets:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._lock.wait(min(remaining, self.WAIT_SLICE_S))
            return True

    def finished(self) -> bool:
        with self._lock:
            if not self._datasets:
                return False
            return all(d.completed() for d in self._datasets.values())

    def training_started(self) -> bool:
        return bool(self._datasets)

    def row_counts(self) -> int:
        """Live shard bookkeeping rows (todo + in-flight leases over
        every dataset) — the self-telemetry state-growth gauge's
        cheap accessor (``export_state`` would serialize every
        shard)."""
        with self._lock:
            return sum(
                len(d.todo) + len(d.doing)
                for d in self._datasets.values()
            )

    def get_dataset_checkpoint(self, dataset_name: str) -> Optional[ShardCheckpoint]:
        with self._lock:
            dataset = self._datasets.get(dataset_name)
            if dataset is None:
                return None
            return ShardCheckpoint(
                dataset_name=dataset_name, content=dataset.checkpoint()
            )

    def restore_dataset_from_checkpoint(self, ckpt: ShardCheckpoint) -> bool:
        with self._lock:
            dataset = self._datasets.get(ckpt.dataset_name)
            if dataset is None:
                return False
            dataset.restore_checkpoint(ckpt.content)
            self._journal_dataset_locked(ckpt.dataset_name)
            self._lock.notify_all()
            return True

    # --------------------------------------------- failover replay
    def export_state(self) -> dict:
        """JSON-safe full state for the compacted snapshot (doing
        leases fold into todo via ``BatchDatasetManager.checkpoint``)."""
        with self._lock:
            return {
                "datasets": {
                    name: {
                        "params": dataclasses.asdict(
                            self._dataset_params[name]
                        ),
                        "ckpt": dataset.checkpoint(),
                    }
                    for name, dataset in self._datasets.items()
                    if name in self._dataset_params
                }
            }

    def restore_state(self, state: dict):
        """Install snapshotted datasets (replay path — not
        re-journaled): recreate each splitter from its params, then
        restore the lease checkpoint.  In-flight (doing) shards come
        back at the FRONT of todo — the unacked leases are re-queued
        exactly as the timeout watcher would have."""
        datasets = state.get("datasets") or {}
        with self._lock:
            cb, self._journal_cb = self._journal_cb, None
            try:
                for name, entry in datasets.items():
                    params = DatasetShardParams(
                        **(entry.get("params") or {})
                    )
                    if name not in self._datasets:
                        shard_size = (
                            params.batch_size
                            * params.num_minibatches_per_shard
                        )
                        splitter = new_dataset_splitter(
                            params.shuffle,
                            shard_size,
                            params.dataset_size,
                            params.num_epochs,
                            params.dataset_name,
                            params.storage_type,
                        )
                        self._datasets[name] = BatchDatasetManager(
                            params.task_type,
                            params.batch_size,
                            splitter,
                        )
                        self._dataset_params[name] = params
                    if entry.get("ckpt"):
                        self._datasets[name].restore_checkpoint(
                            entry["ckpt"]
                        )
            finally:
                self._journal_cb = cb
            self._lock.notify_all()

    def apply_journal_op(self, op: str, args: dict):
        """Re-apply one journaled mutation (replay path)."""
        if op == "dataset":
            self.restore_state(
                {"datasets": {args.get("name", ""): args}}
            )
        elif op == "done":
            with self._lock:
                dataset = self._datasets.get(args.get("name", ""))
                if dataset is not None:
                    dataset.apply_done_for_replay(
                        args.get("shard") or ["", -1, -1],
                        int(args.get("epoch", -1)),
                        int(args.get("step", 0)),
                    )

    def start(self):
        self._watcher = threading.Thread(
            target=self._check_timeout_tasks,
            name="task-timeout-watcher",
            daemon=True,
        )
        self._watcher.start()

    def stop(self):
        self._stopped.set()

    def _check_timeout_tasks(self):
        # Event.wait instead of time.sleep so stop() interrupts the
        # 30 s pause immediately — master shutdown is prompt
        while not self._stopped.is_set():
            with self._lock:
                for dataset in self._datasets.values():
                    for task_id in dataset.get_timeout_tasks(
                        self._task_timeout
                    ):
                        doing = dataset.doing.get(task_id)
                        if doing:
                            logger.warning(
                                "task %s timed out on node %s; re-queue",
                                task_id,
                                doing.node_id,
                            )
                            # doing -> todo: already todo in the
                            # durable view, nothing to journal
                            dataset.recover_task(doing.task)
                            self._lock.notify_all()
            self._stopped.wait(self._check_interval)
