"""Master-side dynamic data sharding service.

Reference parity: ``dlrover/python/master/shard/task_manager.py:37,94,
126,169`` — dispatches shards to workers on demand, recovers shards of
dead workers, re-queues timed-out shards via a watcher thread, and
checkpoints/restores splitter + queue state.
"""

import threading
from typing import Dict, Optional

from dlrover_tpu.common.global_context import Context
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.messages import (
    DatasetShardParams,
    ShardCheckpoint,
    Task,
)
from dlrover_tpu.master.shard.dataset_manager import BatchDatasetManager
from dlrover_tpu.master.shard.dataset_splitter import new_dataset_splitter

_ctx = Context.singleton_instance()


class TaskManager:
    def __init__(self, worker_restart_timeout: float = 0.0,
                 speed_monitor=None, check_interval: float = 30.0):
        self._lock = threading.Lock()
        self._worker_restart_timeout = worker_restart_timeout
        self._datasets: Dict[str, BatchDatasetManager] = {}
        self._speed_monitor = speed_monitor
        self._task_timeout = _ctx.seconds_to_timeout_task
        self._check_interval = check_interval
        self._stopped = threading.Event()
        self._watcher: Optional[threading.Thread] = None
        self._worker_client_hosts: Dict[int, str] = {}

    def new_dataset(self, params: DatasetShardParams):
        with self._lock:
            if params.dataset_name in self._datasets:
                return
            shard_size = params.batch_size * params.num_minibatches_per_shard
            splitter = new_dataset_splitter(
                params.shuffle,
                shard_size,
                params.dataset_size,
                params.num_epochs,
                params.dataset_name,
                params.storage_type,
            )
            self._datasets[params.dataset_name] = BatchDatasetManager(
                params.task_type, params.batch_size, splitter
            )
            logger.info(
                "created dataset %s: size=%s shard=%s epochs=%s",
                params.dataset_name,
                params.dataset_size,
                shard_size,
                params.num_epochs,
            )

    def get_dataset(self, name: str) -> Optional[BatchDatasetManager]:
        return self._datasets.get(name)

    def get_task(self, node_id: int, dataset_name: str) -> Task:
        with self._lock:
            dataset = self._datasets.get(dataset_name)
            if dataset is None:
                return Task()
            return dataset.get_task(node_id)

    def report_task_status(self, dataset_name: str, task_id: int,
                           success: bool):
        with self._lock:
            dataset = self._datasets.get(dataset_name)
            if dataset is None:
                return False
            ok, _ = dataset.report_task_status(task_id, success)
            return ok

    def recover_tasks(self, node_id: int):
        """Recover all doing shards of a dead worker (reference ``:169``)."""
        with self._lock:
            for dataset in self._datasets.values():
                dataset.recover_tasks_of_node(node_id)

    def finished(self) -> bool:
        with self._lock:
            if not self._datasets:
                return False
            return all(d.completed() for d in self._datasets.values())

    def training_started(self) -> bool:
        return bool(self._datasets)

    def get_dataset_checkpoint(self, dataset_name: str) -> Optional[ShardCheckpoint]:
        with self._lock:
            dataset = self._datasets.get(dataset_name)
            if dataset is None:
                return None
            return ShardCheckpoint(
                dataset_name=dataset_name, content=dataset.checkpoint()
            )

    def restore_dataset_from_checkpoint(self, ckpt: ShardCheckpoint) -> bool:
        with self._lock:
            dataset = self._datasets.get(ckpt.dataset_name)
            if dataset is None:
                return False
            dataset.restore_checkpoint(ckpt.content)
            return True

    def start(self):
        self._watcher = threading.Thread(
            target=self._check_timeout_tasks,
            name="task-timeout-watcher",
            daemon=True,
        )
        self._watcher.start()

    def stop(self):
        self._stopped.set()

    def _check_timeout_tasks(self):
        # Event.wait instead of time.sleep so stop() interrupts the
        # 30 s pause immediately — master shutdown is prompt
        while not self._stopped.is_set():
            with self._lock:
                for dataset in self._datasets.values():
                    for task_id in dataset.get_timeout_tasks(
                        self._task_timeout
                    ):
                        doing = dataset.doing.get(task_id)
                        if doing:
                            logger.warning(
                                "task %s timed out on node %s; re-queue",
                                task_id,
                                doing.node_id,
                            )
                            dataset.recover_task(doing.task)
            self._stopped.wait(self._check_interval)
