"""Master-side dynamic data sharding service.

Reference parity: ``dlrover/python/master/shard/task_manager.py:37,94,
126,169`` — dispatches shards to workers on demand, recovers shards of
dead workers, re-queues timed-out shards via a watcher thread, and
checkpoints/restores splitter + queue state.
"""

import threading
import time
from typing import Dict, Optional

from dlrover_tpu.common.global_context import Context
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.messages import (
    DatasetShardParams,
    ShardCheckpoint,
    Task,
    TaskType,
)
from dlrover_tpu.master.shard.dataset_manager import BatchDatasetManager
from dlrover_tpu.master.shard.dataset_splitter import new_dataset_splitter

_ctx = Context.singleton_instance()


class TaskManager:
    #: long-poll wake slice: task availability is mostly event-driven
    #: (new dataset / task ack / recovery all notify) but the timeout
    #: watcher requeues on its own clock, so parked waiters re-check
    WAIT_SLICE_S = 0.5

    def __init__(self, worker_restart_timeout: float = 0.0,
                 speed_monitor=None, check_interval: float = 30.0):
        # a Condition IS a lock for ``with`` purposes; mutations that
        # can turn a WAIT answer into a real task notify long-pollers
        self._lock = threading.Condition()
        self._worker_restart_timeout = worker_restart_timeout
        self._datasets: Dict[str, BatchDatasetManager] = {}
        self._speed_monitor = speed_monitor
        self._task_timeout = _ctx.seconds_to_timeout_task
        self._check_interval = check_interval
        self._stopped = threading.Event()
        self._watcher: Optional[threading.Thread] = None
        self._worker_client_hosts: Dict[int, str] = {}

    def new_dataset(self, params: DatasetShardParams):
        with self._lock:
            if params.dataset_name in self._datasets:
                return
            shard_size = params.batch_size * params.num_minibatches_per_shard
            splitter = new_dataset_splitter(
                params.shuffle,
                shard_size,
                params.dataset_size,
                params.num_epochs,
                params.dataset_name,
                params.storage_type,
            )
            self._datasets[params.dataset_name] = BatchDatasetManager(
                params.task_type, params.batch_size, splitter
            )
            self._lock.notify_all()
            logger.info(
                "created dataset %s: size=%s shard=%s epochs=%s",
                params.dataset_name,
                params.dataset_size,
                shard_size,
                params.num_epochs,
            )

    def get_dataset(self, name: str) -> Optional[BatchDatasetManager]:
        return self._datasets.get(name)

    def get_task(self, node_id: int, dataset_name: str) -> Task:
        with self._lock:
            dataset = self._datasets.get(dataset_name)
            if dataset is None:
                return Task()
            return dataset.get_task(node_id)

    def report_task_status(self, dataset_name: str, task_id: int,
                           success: bool):
        with self._lock:
            dataset = self._datasets.get(dataset_name)
            if dataset is None:
                return False
            ok, _ = dataset.report_task_status(task_id, success)
            # a failure requeues the shard and an ack can roll the
            # splitter into the next epoch — either can turn a parked
            # WAIT long-poller's answer into a real task
            self._lock.notify_all()
            return ok

    def recover_tasks(self, node_id: int):
        """Recover all doing shards of a dead worker (reference ``:169``)."""
        with self._lock:
            for dataset in self._datasets.values():
                dataset.recover_tasks_of_node(node_id)
            self._lock.notify_all()

    def wait_task(self, node_id: int, dataset_name: str,
                  wait_timeout: float = 0.0) -> Task:
        """Long-poll ``get_task``: while the dataset would only hand
        out WAIT tasks, park on the condition (woken by acks/failures/
        recovery) up to ``wait_timeout`` — the WAIT answer then still
        goes out, so the client's loop semantics are unchanged."""
        deadline = time.monotonic() + max(wait_timeout, 0.0)
        while True:
            task = self.get_task(node_id, dataset_name)
            if task.task_type != TaskType.WAIT:
                return task
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return task
            with self._lock:
                self._lock.wait(min(remaining, self.WAIT_SLICE_S))

    def wait_training_started(self, wait_timeout: float = 0.0) -> bool:
        """Long-poll ``training_started``: block until the first
        dataset registration flips it (or the timeout elapses)."""
        deadline = time.monotonic() + max(wait_timeout, 0.0)
        with self._lock:
            while not self._datasets:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._lock.wait(min(remaining, self.WAIT_SLICE_S))
            return True

    def finished(self) -> bool:
        with self._lock:
            if not self._datasets:
                return False
            return all(d.completed() for d in self._datasets.values())

    def training_started(self) -> bool:
        return bool(self._datasets)

    def get_dataset_checkpoint(self, dataset_name: str) -> Optional[ShardCheckpoint]:
        with self._lock:
            dataset = self._datasets.get(dataset_name)
            if dataset is None:
                return None
            return ShardCheckpoint(
                dataset_name=dataset_name, content=dataset.checkpoint()
            )

    def restore_dataset_from_checkpoint(self, ckpt: ShardCheckpoint) -> bool:
        with self._lock:
            dataset = self._datasets.get(ckpt.dataset_name)
            if dataset is None:
                return False
            dataset.restore_checkpoint(ckpt.content)
            self._lock.notify_all()
            return True

    def start(self):
        self._watcher = threading.Thread(
            target=self._check_timeout_tasks,
            name="task-timeout-watcher",
            daemon=True,
        )
        self._watcher.start()

    def stop(self):
        self._stopped.set()

    def _check_timeout_tasks(self):
        # Event.wait instead of time.sleep so stop() interrupts the
        # 30 s pause immediately — master shutdown is prompt
        while not self._stopped.is_set():
            with self._lock:
                for dataset in self._datasets.values():
                    for task_id in dataset.get_timeout_tasks(
                        self._task_timeout
                    ):
                        doing = dataset.doing.get(task_id)
                        if doing:
                            logger.warning(
                                "task %s timed out on node %s; re-queue",
                                task_id,
                                doing.node_id,
                            )
                            dataset.recover_task(doing.task)
                            self._lock.notify_all()
            self._stopped.wait(self._check_interval)
