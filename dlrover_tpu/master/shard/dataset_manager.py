"""Per-dataset shard bookkeeping: todo/doing queues with recovery.

Reference parity: ``dlrover/python/master/shard/batch_dataset_manager.py``
(+ the streaming variant).  The doubt-shard recovery protocol: a shard
moves todo -> doing on dispatch; if the worker dies or times out the
shard goes back to todo, so no sample is lost across elasticity events.
"""

import time
from abc import ABCMeta, abstractmethod
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.messages import Task, TaskType
from dlrover_tpu.master.shard.dataset_splitter import DatasetSplitter


class DoingTask:
    def __init__(self, task: Task, node_id: int, start_time: float):
        self.task = task
        self.node_id = node_id
        self.start_time = start_time


class DatasetManager(metaclass=ABCMeta):
    def __init__(self, task_type: str, batch_size: int,
                 splitter: DatasetSplitter):
        self._task_type = task_type
        self._batch_size = batch_size
        self._splitter = splitter
        self.todo: List[Task] = []
        self.doing: Dict[int, DoingTask] = {}

    @abstractmethod
    def get_task(self, node_id: int) -> Task:
        ...

    @abstractmethod
    def completed(self) -> bool:
        ...

    def get_epoch(self) -> int:
        return self._splitter.epoch


class BatchDatasetManager(DatasetManager):
    def __init__(self, task_type: str, batch_size: int,
                 splitter: DatasetSplitter):
        super().__init__(task_type, batch_size, splitter)
        self._task_id = 0
        self._completed_step = 0
        self._max_task_completed_time = 0.0
        #: bumped whenever the splitter produced a new todo batch —
        #: the failover journal records full state exactly then (the
        #: splitter position moved), and O(1) deltas otherwise
        self.refill_count = 0

    def get_task(self, node_id: int) -> Task:
        """Pop the next todo task; WAIT if dispatching is exhausted but
        the epoch may still produce more shards."""
        if not self.todo and not self._splitter.epoch_finished():
            self._create_tasks()
        if self.todo:
            task = self.todo.pop(0)
            self.doing[task.task_id] = DoingTask(
                task, node_id, time.time()
            )
            return task
        if not self.completed():
            return Task(task_id=-1, task_type=TaskType.WAIT)
        return Task()

    def _create_tasks(self):
        self.refill_count += 1
        self._splitter.create_shards()
        for shard in self._splitter.get_shards():
            task = Task(
                task_id=self._task_id,
                task_type=self._task_type,
                shard=shard,
            )
            self._task_id += 1
            self.todo.append(task)

    def report_task_status(self, task_id: int, success: bool) -> Tuple[bool, Optional[DoingTask]]:
        doing_task = self.doing.pop(task_id, None)
        if doing_task is None:
            logger.warning("unknown task %s reported", task_id)
            return False, None
        if not success:
            logger.warning(
                "task %s failed on node %s; recovering",
                task_id,
                doing_task.node_id,
            )
            self.todo.insert(0, doing_task.task)
            return False, doing_task
        elapsed = time.time() - doing_task.start_time
        self._max_task_completed_time = max(
            self._max_task_completed_time, elapsed
        )
        if doing_task.task.task_type == TaskType.TRAINING:
            shard = doing_task.task.shard
            self._completed_step += (
                (shard.end - shard.start) // max(self._batch_size, 1)
            )
        return True, doing_task

    def recover_task(self, task: Task):
        """Put a dispatched-but-unfinished task back (dead worker)."""
        if task.task_id in self.doing:
            del self.doing[task.task_id]
        self.todo.insert(0, task)

    def recover_tasks_of_node(self, node_id: int):
        for task_id in [
            tid
            for tid, dt in self.doing.items()
            if dt.node_id == node_id
        ]:
            doing = self.doing.pop(task_id)
            self.todo.insert(0, doing.task)
            logger.info(
                "recover task %s of dead node %s", task_id, node_id
            )

    def get_timeout_tasks(self, timeout: float) -> List[int]:
        now = time.time()
        return [
            tid
            for tid, dt in self.doing.items()
            if now - dt.start_time > timeout
        ]

    def completed(self) -> bool:
        return (
            self._splitter.epoch_finished()
            and not self.todo
            and not self.doing
        )

    def checkpoint(self) -> str:
        import json

        # doubt shards: both todo and doing go back to todo on restore;
        # the full shard is kept (name for streaming partitions,
        # record_indices for shuffled text datasets)
        def _shard(s):
            return [s.name, s.start, s.end, s.record_indices]

        todo_shards = [
            _shard(t.task.shard) for t in self.doing.values()
        ] + [_shard(t.shard) for t in self.todo]
        return json.dumps(
            {
                "todo": todo_shards,
                "splitter": self._splitter.checkpoint(),
                "task_id": self._task_id,
            }
        )

    def restore_checkpoint(self, checkpoint: str):
        import json

        from dlrover_tpu.common.messages import DataShard

        state = json.loads(checkpoint)
        self._splitter.restore_checkpoint(state["splitter"])
        self._task_id = state.get("task_id", 0)
        self.todo.clear()
        self.doing.clear()
        for name, lo, hi, indices in state["todo"]:
            self.todo.append(
                Task(
                    task_id=self._task_id,
                    task_type=self._task_type,
                    shard=DataShard(name, lo, hi,
                                    record_indices=indices),
                )
            )
            self._task_id += 1

    def apply_done_for_replay(
        self, shard_key, epoch: int, completed_step: int
    ):
        """Replay one journaled successful ack: remove the todo task
        whose shard matches ``shard_key`` (``[name, start, end]`` —
        stable across the task-id renumbering ``restore_checkpoint``
        performs) and adopt the recorded progress.  ``epoch`` guards
        the snapshot race: ranges recur every epoch, so a stale delta
        racing a newer-epoch snapshot must not eat the new epoch's
        shard.  Idempotent — a delta the snapshot already folded in
        finds no match and ``max`` keeps the newer step."""
        name, lo, hi = shard_key
        if epoch == self._splitter.epoch:
            for i, task in enumerate(self.todo):
                s = task.shard
                if (s.name, s.start, s.end) == (name, lo, hi):
                    del self.todo[i]
                    break
        self._completed_step = max(
            self._completed_step, int(completed_step)
        )

    @property
    def completed_step(self) -> int:
        return self._completed_step
