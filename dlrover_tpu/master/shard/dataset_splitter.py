"""Dataset splitters: partition a dataset into shards the master hands
out to workers.

Reference parity: ``dlrover/python/master/shard/dataset_splitter.py:90,
144,257,359`` (DatasetSplitter ABC, Table/Text/Streaming splitters).
Shards are index ranges — the TPU data path feeds them to per-host input
pipelines; with dynamic shape-stable batches the shard boundary never
leaks into jit-land.
"""

import json
import random
from abc import ABCMeta, abstractmethod
from typing import List, Optional

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.messages import DataShard


class PartitionOffsets:
    """Unconsumed sample offsets of a streaming dataset."""

    def __init__(self, partition_offsets: Optional[dict] = None):
        self.partition_offsets = partition_offsets or {}


class DatasetSplitter(metaclass=ABCMeta):
    def __init__(self, dataset_name: str, dataset_size: int, shard_size: int,
                 num_epochs: int):
        self.dataset_name = dataset_name
        self.dataset_size = dataset_size
        self.shard_size = shard_size
        self.num_epochs = num_epochs
        self.epoch = 0

    @abstractmethod
    def create_shards(self):
        ...

    @abstractmethod
    def get_shards(self) -> List[DataShard]:
        ...

    @abstractmethod
    def checkpoint(self) -> str:
        ...

    @abstractmethod
    def restore_checkpoint(self, checkpoint: str):
        ...

    def epoch_finished(self) -> bool:
        return self.epoch >= self.num_epochs


class TableDatasetSplitter(DatasetSplitter):
    """Contiguous index-range shards of a table-like dataset; optional
    epoch-level shuffle of shard order (reference ``:144``)."""

    def __init__(
        self,
        dataset_name: str,
        dataset_size: int,
        shard_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
        max_shard_count: int = 50000,
    ):
        super().__init__(dataset_name, dataset_size, shard_size, num_epochs)
        self._shuffle = shuffle
        self._max_shard_count = max_shard_count
        self._subepoch_num_per_epoch = 0
        self._shards: List[DataShard] = []
        self._subepoch_idx = 0

    def get_shards(self) -> List[DataShard]:
        return self._shards

    def create_shards(self):
        logger.info(
            "create shards for dataset %s size=%s shard_size=%s epoch=%s",
            self.dataset_name,
            self.dataset_size,
            self.shard_size,
            self.epoch,
        )
        shard_count = (
            self.dataset_size + self.shard_size - 1
        ) // self.shard_size
        if shard_count <= self._max_shard_count:
            if not self._shards:
                self.epoch += 1
                self._shards = self._create_shards_with_range(
                    0, self.dataset_size
                )
            else:
                self.epoch += 1
                if self._shuffle:
                    random.shuffle(self._shards)
        else:
            # split an epoch into sub-epochs to bound the in-memory
            # shard table (reference ``:201``)
            if self._subepoch_num_per_epoch == 0:
                self._subepoch_num_per_epoch = (
                    shard_count + self._max_shard_count - 1
                ) // self._max_shard_count
            if self._subepoch_idx % self._subepoch_num_per_epoch == 0:
                self.epoch += 1
            subepoch_size = self._max_shard_count * self.shard_size
            start = (
                self._subepoch_idx % self._subepoch_num_per_epoch
            ) * subepoch_size
            end = min(start + subepoch_size, self.dataset_size)
            self._subepoch_idx += 1
            self._shards = self._create_shards_with_range(start, end)

    def _create_shards_with_range(self, start: int, end: int):
        shards = []
        for lo in range(start, end, self.shard_size):
            hi = min(lo + self.shard_size, end)
            shards.append(DataShard(self.dataset_name, lo, hi))
        if self._shuffle:
            random.shuffle(shards)
        return shards

    def checkpoint(self) -> str:
        return json.dumps(
            {
                "epoch": self.epoch,
                "subepoch_idx": self._subepoch_idx,
                "shards": [[s.start, s.end] for s in self._shards],
            }
        )

    def restore_checkpoint(self, checkpoint: str):
        state = json.loads(checkpoint)
        self.epoch = state["epoch"]
        self._subepoch_idx = state.get("subepoch_idx", 0)
        self._shards = [
            DataShard(self.dataset_name, lo, hi)
            for lo, hi in state["shards"]
        ]


class TextDatasetSplitter(DatasetSplitter):
    """Shards carrying explicit (optionally shuffled) per-record indices
    of a text file (reference ``:257``)."""

    def __init__(
        self,
        dataset_name: str,
        dataset_size: int,
        shard_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
    ):
        super().__init__(dataset_name, dataset_size, shard_size, num_epochs)
        self._shuffle = shuffle
        self._shards: List[DataShard] = []

    def get_shards(self) -> List[DataShard]:
        return self._shards

    def create_shards(self):
        self.epoch += 1
        indices = list(range(self.dataset_size))
        if self._shuffle:
            random.shuffle(indices)
        shards = []
        for lo in range(0, self.dataset_size, self.shard_size):
            hi = min(lo + self.shard_size, self.dataset_size)
            shards.append(
                DataShard(
                    self.dataset_name,
                    lo,
                    hi,
                    record_indices=indices[lo:hi],
                )
            )
        self._shards = shards

    def checkpoint(self) -> str:
        return json.dumps(
            {
                "epoch": self.epoch,
                "shards": [
                    [s.start, s.end, s.record_indices] for s in self._shards
                ],
            }
        )

    def restore_checkpoint(self, checkpoint: str):
        state = json.loads(checkpoint)
        self.epoch = state["epoch"]
        self._shards = [
            DataShard(self.dataset_name, lo, hi, record_indices=idx)
            for lo, hi, idx in state["shards"]
        ]


class StreamingDatasetSplitter(DatasetSplitter):
    """Shards over an unbounded stream: consumes a moving window of
    offsets, dataset_size grows as data arrives (reference ``:359``)."""

    def __init__(
        self,
        dataset_name: str,
        shard_size: int,
        partition_offset: Optional[PartitionOffsets] = None,
        dataset_size: int = -1,
        fetch_data_size: int = 10000,
    ):
        super().__init__(dataset_name, dataset_size, shard_size, 1)
        self._partition_offset = partition_offset or PartitionOffsets()
        self._fetch_data_size = fetch_data_size
        self._shards: List[DataShard] = []

    def get_shards(self) -> List[DataShard]:
        return self._shards

    def epoch_finished(self) -> bool:
        return self.dataset_size == 0

    def create_shards(self):
        shards = []
        if self.dataset_size > 0:
            fetch = min(self.dataset_size, self._fetch_data_size)
            self.dataset_size -= fetch
        else:
            fetch = self._fetch_data_size
        for name, offset in list(
            self._partition_offset.partition_offsets.items()
        ):
            for lo in range(offset, offset + fetch, self.shard_size):
                hi = min(lo + self.shard_size, offset + fetch)
                shards.append(DataShard(str(name), lo, hi))
            self._partition_offset.partition_offsets[name] = offset + fetch
        self._shards = shards

    def checkpoint(self) -> str:
        return json.dumps(
            {
                "epoch": self.epoch,
                "dataset_size": self.dataset_size,
                "partition_offsets": (
                    self._partition_offset.partition_offsets
                ),
                "shards": [[s.name, s.start, s.end] for s in self._shards],
            }
        )

    def restore_checkpoint(self, checkpoint: str):
        state = json.loads(checkpoint)
        self.epoch = state["epoch"]
        self.dataset_size = state["dataset_size"]
        self._partition_offset = PartitionOffsets(
            state["partition_offsets"]
        )
        self._shards = [
            DataShard(name, lo, hi) for name, lo, hi in state["shards"]
        ]


def new_dataset_splitter(
    shuffle: bool,
    shard_size: int,
    dataset_size: int,
    num_epochs: int,
    dataset_name: str,
    storage_type: str = "table",
) -> DatasetSplitter:
    """Factory matching the reference's ``new_dataset_splitter``."""
    if storage_type in ("", "table"):
        return TableDatasetSplitter(
            dataset_name, dataset_size, shard_size, num_epochs, shuffle
        )
    if storage_type == "text":
        return TextDatasetSplitter(
            dataset_name, dataset_size, shard_size, num_epochs, shuffle
        )
    if storage_type == "stream":
        return StreamingDatasetSplitter(
            dataset_name,
            shard_size,
            # without explicit partitions, consume one default partition
            # from offset 0 so shards actually get produced
            partition_offset=PartitionOffsets({dataset_name: 0}),
            dataset_size=dataset_size,
        )
    raise ValueError(f"unknown dataset storage type {storage_type}")
