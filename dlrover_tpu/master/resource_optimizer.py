"""Resource optimization: the Brain algorithm set for allreduce jobs.

Reference parity: the Go Brain service's optimizer-algorithm plugin
registry (``dlrover/go/brain/pkg/optimizer/implementation/
optalgorithm/``) restricted to the allreduce-relevant set, plus
``dlrover/python/master/resource/local_optimizer.py:66``
(``PSLocalOptimizer`` stage plans / OOM recovery ``:98`` /
worker-speed-ratio scaling ``:250``):

- worker-create        (``optimize_job_worker_create_resource.go``):
  the initial scale plan.
- worker-resource      (``optimize_job_worker_resource.go:400``):
  linear-model throughput extrapolation from SpeedMonitor samples —
  grow while the marginal speedup stays near-linear, settle back to
  the best-known world size on diminishing returns.
- worker-oom           (``optimize_job_worker_create_oom_resource.go``):
  relaunch OOM-killed workers with grown host memory.
- straggler-migrate    (``optimize_job_hot_ps_resource.go`` dual for
  allreduce): migrate nodes the network-check rounds flagged slow.

TPU form: the unit of scaling is a whole TPU-VM worker (chips come in
fixed slices), so plans adjust *worker count* within [min, max].
"""

import threading
import time
from abc import ABCMeta, abstractmethod
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.messages import ScalePlan


@dataclass
class SpeedSample:
    worker_num: int
    records_per_sec: float


class JobStage:
    CREATE = "create"
    RUNNING = "running"


@dataclass
class JobMeta:
    """Everything an optimize algorithm may consult — the Brain's
    datastore row for one job, assembled by the auto-scaler each
    cycle."""

    stage: str = JobStage.RUNNING
    min_workers: int = 1
    max_workers: int = 1
    current_workers: int = 0
    # best observed throughput per world size (records/sec)
    speed_samples: Dict[int, float] = field(default_factory=dict)
    # node names the health-check rounds flagged as stragglers
    stragglers: List[str] = field(default_factory=list)
    # node name -> current memory MB for OOM-killed workers
    oom_nodes: Dict[str, int] = field(default_factory=dict)


class OptimizeAlgorithm(metaclass=ABCMeta):
    """One pluggable optimization rule (Brain's ``OptimizeAlgorithm``
    interface; plugins registered by name)."""

    name: str = ""

    @abstractmethod
    def optimize(self, meta: JobMeta) -> Optional[ScalePlan]:
        ...


_ALGORITHMS: Dict[str, type] = {}


def register_algorithm(cls):
    _ALGORITHMS[cls.name] = cls
    return cls


def get_algorithm(name: str) -> Optional[type]:
    return _ALGORITHMS.get(name)


@register_algorithm
class WorkerCreateResource(OptimizeAlgorithm):
    """Initial plan: launch the full worker window (elasticity shrinks
    later if throughput says so)."""

    name = "optimize_worker_create_resource"

    def optimize(self, meta: JobMeta) -> Optional[ScalePlan]:
        if meta.stage != JobStage.CREATE:
            return None
        plan = ScalePlan()
        plan.node_group_resources[NodeType.WORKER] = {
            "count": meta.max_workers
        }
        return plan


@register_algorithm
class WorkerResource(OptimizeAlgorithm):
    """Throughput-driven worker-count tuning.

    With >=3 observed world sizes the decision uses a FITTED scaling
    model extrapolated to unseen sizes (the reference Brain fits a
    linear throughput model over persisted history,
    ``optimize_job_worker_resource.go:400``): least-squares of
    ``n/speed = a + b*n`` — the Amdahl/serial-fraction form, linear in
    exactly the quantity a synchronous data-parallel job degrades in —
    then jump toward the LARGEST size whose predicted marginal gain
    still clears ``min_marginal_gain``.  The durable speed history
    (``master/datastore.py``) makes the fit meaningful across master
    restarts.

    With only 2 sizes, fall back to the local slope between them
    (the reference's worker-speed-ratio); while marginal throughput
    stays above the threshold, grow by up to 25% per cycle."""

    name = "optimize_worker_resource"

    # never jump more than this factor past the current size in one
    # plan: the fit extrapolates, reality gets a vote at each stop
    MAX_JUMP_FACTOR = 2.0

    def __init__(self, min_marginal_gain: float = 0.6,
                 growth_ratio: float = 0.25):
        self._gain = min_marginal_gain
        self._growth = growth_ratio

    def _fit_knee(self, samples: Dict[int, float],
                  max_workers: int) -> Optional[int]:
        """Fit n/speed = a + b*n; return the largest n whose predicted
        marginal gain clears the threshold (None = fit unusable)."""
        sizes = sorted(samples)
        xs = [float(n) for n in sizes]
        ys = [n / max(samples[n], 1e-9) for n in sizes]
        k = len(xs)
        sx, sy = sum(xs), sum(ys)
        sxx = sum(x * x for x in xs)
        sxy = sum(x * y for x, y in zip(xs, ys))
        denom = k * sxx - sx * sx
        if abs(denom) < 1e-12:
            return None
        b = (k * sxy - sx * sy) / denom
        a = (sy - b * sx) / k

        def speed(n: float) -> float:
            d = a + b * n
            return n / d if d > 1e-12 else 0.0

        if b <= 0:
            # no measurable serial fraction yet: predicted scaling is
            # (super)linear — the knee is past max_workers
            return max_workers
        best = None
        for n in range(1, max_workers + 1):
            per_worker = speed(n) / n
            marginal = (speed(n + 1) - speed(n)) / max(
                per_worker, 1e-12
            )
            if marginal >= self._gain:
                best = n + 1
        return best

    @staticmethod
    def _best_known(meta: JobMeta, tolerance: float = 0.05) -> int:
        """The SMALLEST size within ``tolerance`` of the peak observed
        throughput — scale-back exists to shed workers that buy almost
        nothing, so near-ties resolve to fewer workers."""
        if not meta.speed_samples:
            return meta.min_workers
        peak = max(meta.speed_samples.values())
        ok = [
            n
            for n, v in meta.speed_samples.items()
            if v >= (1.0 - tolerance) * peak
        ]
        return min(ok) if ok else meta.min_workers

    def optimize(self, meta: JobMeta) -> Optional[ScalePlan]:
        if meta.stage != JobStage.RUNNING:
            return None
        samples = meta.speed_samples
        if not samples:
            return None
        sizes = sorted(samples)
        # the ACTUAL world size, not the max-ever-sampled one: after a
        # settle the stale larger sample would otherwise re-emit the
        # same scale-back plan every cycle forever
        current = meta.current_workers or sizes[-1]
        if len(sizes) >= 3:
            target = self._fit_knee(samples, meta.max_workers)
            if target is not None:
                target = max(target, meta.min_workers)
                if target > current:
                    cap = int(current * self.MAX_JUMP_FACTOR)
                    count = min(target, cap, meta.max_workers)
                    if count == current:
                        return None  # capped at where we already are
                    plan = ScalePlan()
                    plan.node_group_resources[NodeType.WORKER] = {
                        "count": count
                    }
                    logger.info(
                        "fitted scaling model: knee at %d workers "
                        "(current %d)", target, current,
                    )
                    return plan
                if target < current:
                    settle = max(
                        min(target, self._best_known(meta)),
                        meta.min_workers,
                    )
                    if settle != current:
                        plan = ScalePlan()
                        plan.node_group_resources[
                            NodeType.WORKER
                        ] = {"count": settle}
                        logger.info(
                            "fitted scaling model: settling at %d "
                            "workers (current %d)", settle, current,
                        )
                        return plan
                return None  # already at the predicted knee
        if len(sizes) >= 2:
            # stop/settle decision uses the LOCAL slope between the two
            # largest observed sizes (the reference's worker-speed-ratio
            # compares speed before/after the last grow step) — a global
            # least-squares fit smooths the knee away and keeps growing
            # past it
            n0, n1 = sizes[-2], sizes[-1]
            local_slope = (samples[n1] - samples[n0]) / (n1 - n0)
            ref = current if current in samples else n1
            per_worker_now = samples[ref] / ref
            # marginal value of one more worker, as a fraction of the
            # current per-worker throughput (1.0 == perfectly linear)
            marginal = local_slope / max(per_worker_now, 1e-9)
            if marginal < self._gain:
                best_n = self._best_known(meta)
                if best_n != current:
                    plan = ScalePlan()
                    plan.node_group_resources[NodeType.WORKER] = {
                        "count": max(best_n, meta.min_workers)
                    }
                    logger.info(
                        "scale back to %d workers (marginal %.2f)",
                        best_n, marginal,
                    )
                    return plan
                return None  # diminishing returns: stop growing
        if current < meta.max_workers:
            step = max(1, int(current * self._growth))
            plan = ScalePlan()
            plan.node_group_resources[NodeType.WORKER] = {
                "count": min(current + step, meta.max_workers)
            }
            return plan
        return None


@register_algorithm
class WorkerOomResource(OptimizeAlgorithm):
    """Relaunch OOM-killed workers with grown host memory
    (reference ``local_optimizer.py:98`` OOM ladder)."""

    name = "optimize_worker_oom_resource"

    def __init__(self, oom_memory_factor: float = 1.5):
        self._factor = oom_memory_factor

    def optimize(self, meta: JobMeta) -> Optional[ScalePlan]:
        if not meta.oom_nodes:
            return None
        plan = ScalePlan()
        for node, memory_mb in meta.oom_nodes.items():
            plan.remove_nodes.append(node)
            plan.launch_nodes.append(
                {
                    "type": NodeType.WORKER,
                    "memory": int(memory_mb * self._factor),
                }
            )
        return plan


@register_algorithm
class StragglerMigrate(OptimizeAlgorithm):
    """Migrate nodes the health-check rounds flagged slow (the
    allreduce dual of hot-PS migration: a synchronous mesh runs at the
    slowest node's speed, so one straggler taxes the whole job)."""

    name = "optimize_straggler_migrate"

    def optimize(self, meta: JobMeta) -> Optional[ScalePlan]:
        if not meta.stragglers:
            return None
        plan = ScalePlan()
        for node in meta.stragglers:
            plan.migrate_nodes[str(node)] = {"type": NodeType.WORKER}
        return plan


def merge_plans(plans: List[Optional[ScalePlan]]) -> Optional[ScalePlan]:
    merged = ScalePlan()
    for p in plans:
        if p is None:
            continue
        merged.node_group_resources.update(p.node_group_resources)
        merged.launch_nodes.extend(p.launch_nodes)
        merged.remove_nodes.extend(p.remove_nodes)
        merged.migrate_nodes.update(p.migrate_nodes)
    return None if merged.is_empty() else merged


class ResourceOptimizer(metaclass=ABCMeta):
    @abstractmethod
    def generate_plan(self, stage: str) -> Optional[ScalePlan]:
        ...


class LocalAllreduceOptimizer(ResourceOptimizer):
    """The local Brain: runs the registered algorithm set against the
    job's observed state (reference ``BrainResoureOptimizer`` role
    without the external service; same algorithms, in-process)."""

    def __init__(
        self,
        min_workers: int = 1,
        max_workers: int = 1,
        min_marginal_gain: float = 0.6,
        oom_memory_factor: float = 1.5,
        datastore=None,
        job_name: str = "default",
    ):
        """``datastore``/``job_name``: persist speed samples per job
        (reference: the Go Brain's MySQL job-metrics recorders) so a
        restarted master's WorkerResource decisions start from the
        job's full observed speed curve, not an empty map.  Defaults
        to the process datastore when ``DLROVER_TPU_BRAIN_DB`` is
        set."""
        if datastore is None:
            from dlrover_tpu.master.datastore import (
                get_default_datastore,
            )

            datastore = get_default_datastore()
        self._datastore = datastore
        self._job_name = job_name
        self._min = min_workers
        self._max = max_workers
        self._samples: Dict[int, float] = {}
        if datastore is not None:
            try:
                self._samples.update(
                    datastore.speed_history(job_name)
                )
            except Exception as e:  # noqa: BLE001
                logger.warning(
                    "speed-history restore failed: %s", e
                )
        self._current_workers = 0
        self._stragglers: List[str] = []
        self._oom_nodes: Dict[str, int] = {}
        self._algorithms: List[OptimizeAlgorithm] = [
            WorkerCreateResource(),
            WorkerResource(min_marginal_gain=min_marginal_gain),
            WorkerOomResource(oom_memory_factor=oom_memory_factor),
            StragglerMigrate(),
        ]
        self._oom_factor = oom_memory_factor

    # -- observation feeds (the Brain's datastore writes) ---------------
    def record_speed(self, worker_num: int, records_per_sec: float):
        if worker_num <= 0 or records_per_sec <= 0:
            return
        prev = self._samples.get(worker_num, 0.0)
        self._samples[worker_num] = max(prev, records_per_sec)
        self._current_workers = worker_num
        if self._datastore is not None and records_per_sec > prev:
            try:
                self._datastore.record_speed(
                    self._job_name, worker_num, records_per_sec
                )
            except Exception as e:  # noqa: BLE001
                logger.warning("speed persist failed: %s", e)

    def set_current_workers(self, worker_num: int):
        if worker_num > 0:
            self._current_workers = worker_num

    def report_stragglers(self, nodes: List[str]):
        self._stragglers = list(nodes)

    def report_oom(self, node_name: str, current_memory_mb: int):
        self._oom_nodes[node_name] = current_memory_mb

    # -- plan generation -------------------------------------------------
    def _meta(self, stage: str) -> JobMeta:
        sizes = sorted(self._samples)
        return JobMeta(
            stage=stage,
            min_workers=self._min,
            max_workers=self._max,
            current_workers=self._current_workers
            or (sizes[-1] if sizes else 0),
            speed_samples=dict(self._samples),
            stragglers=list(self._stragglers),
            oom_nodes=dict(self._oom_nodes),
        )

    def generate_plan(self, stage: str) -> Optional[ScalePlan]:
        meta = self._meta(stage)
        plans = [alg.optimize(meta) for alg in self._algorithms]
        plan = merge_plans(plans)
        # one-shot signals are consumed by the plan they produced
        self._stragglers = []
        self._oom_nodes = {}
        return plan

    def oom_recovery_plan(self, node_name: str,
                          current_memory_mb: int) -> ScalePlan:
        """Immediate OOM relaunch plan (outside the periodic cycle)."""
        self.report_oom(node_name, current_memory_mb)
        meta = self._meta(JobStage.RUNNING)
        plan = WorkerOomResource(self._oom_factor).optimize(meta)
        self._oom_nodes = {}
        return plan


# ---------------------------------------------------------------------------
# the observatory-fed Brain (DLROVER_TPU_BRAIN; ROADMAP item 1)
# ---------------------------------------------------------------------------
#
# The seed optimizer above reads one scalar (records/sec from the
# SpeedMonitor) and its only actuator is a pod-count plan.  The Brain
# variant below consumes the PR-8 observatory derivations and the
# goodput ledger, and every verdict is a single explicit
# :class:`BrainDecision` the executor (``master/brain.py``) turns into
# ONE planned action — fence + cooperative drain + re-solve +
# resharded restore — instead of an emergent restart.  Rules:
#
# - confirmed straggler / hang-watchdog conclusion -> drain_replace
# - chronic data-stall share                        -> shrink
# - near-linear step-time scaling + spare capacity  -> grow
#
# Everything is hysteresis/cooldown-guarded, and the whole mutable
# rule state (streaks, last decision, the in-flight action) exports /
# restores through the PR-7 ``ControlPlaneJournal`` so a master
# failover mid-action resumes or abandons it instead of flip-flopping.

#: BrainDecision.action vocabulary
ACTION_GROW = "grow"
ACTION_SHRINK = "shrink"
ACTION_DRAIN_REPLACE = "drain_replace"

#: capacity direction per action (hysteresis keys on it: an opposite-
#: direction decision needs twice the cooldown)
_DIRECTION = {
    ACTION_GROW: "up",
    ACTION_SHRINK: "down",
    ACTION_DRAIN_REPLACE: "down",
}

#: execution outcomes (scale_execute labels / journal records)
OUTCOME_DONE = "done"
OUTCOME_FENCED_FALLBACK = "fenced_fallback"
OUTCOME_ABANDONED = "abandoned"


@dataclass
class ObservatorySignals:
    """One decision cycle's inputs, assembled by the auto-scaler from
    the health engine + rendezvous manager + ledger (kept a plain
    dataclass so the rule tests feed it directly)."""

    #: node ranks of the latest completed world (rank order)
    world: List[int] = field(default_factory=list)
    min_nodes: int = 1
    max_nodes: int = 1
    #: (node, score) past the straggler ratio (HealthEngine.stragglers)
    stragglers: List[tuple] = field(default_factory=list)
    #: (node, silence_s) hang-watchdog verdicts
    hangs: List[tuple] = field(default_factory=list)
    #: node -> {stage: share} windowed data-stall shares
    stall_shares: Dict[int, Dict[str, float]] = field(
        default_factory=dict
    )
    #: across-node median step-time EWMA (0 = not enough steps yet)
    median_step_time_s: float = 0.0
    #: live preemption fences (nodes already on their way out)
    fenced: List[int] = field(default_factory=list)
    #: the executor can CREATE nodes (a scaler is attached)
    can_launch: bool = False
    #: goodput ledger snapshot (advisory context, journaled with the
    #: decision so every verdict carries the evidence it saw)
    goodput: float = 0.0
    #: wall clock (injected so rule tests control time)
    now: float = 0.0


@dataclass
class BrainDecision:
    """One planned action: what rule fired, against whom, and the
    world transition it intends.  Serialized verbatim into the
    journal and the ``scale_decision`` / ``scale_execute`` events."""

    decision_id: int = 0
    action: str = ""
    reason: str = ""
    node: int = -1  # target rank (drain/shrink victim; -1 for grow)
    from_world: int = 0
    to_world: int = 0
    made_at: float = 0.0
    goodput: float = 0.0

    @property
    def direction(self) -> str:
        return _DIRECTION.get(self.action, "none")

    def to_dict(self) -> dict:
        return {
            "decision_id": self.decision_id,
            "action": self.action,
            "reason": self.reason,
            "node": self.node,
            "from_world": self.from_world,
            "to_world": self.to_world,
            "made_at": self.made_at,
            "goodput": self.goodput,
        }

    @classmethod
    def from_dict(cls, data: dict) -> "BrainDecision":
        known = {
            k: v
            for k, v in (data or {}).items()
            if k in cls.__dataclass_fields__
        }
        return cls(**known)


class ObservatoryBrainOptimizer:
    """The Brain's rule engine: ``decide()`` turns one cycle's
    :class:`ObservatorySignals` into at most ONE
    :class:`BrainDecision`, guarded by sustain streaks (a single
    noisy snapshot is not a verdict), a post-execution cooldown, and
    2x-cooldown hysteresis against direction flips.  All mutable
    state round-trips through ``export_state``/``restore_state`` (the
    journal component contract)."""

    #: step-time EWMA blend for the per-world scaling history
    HISTORY_ALPHA = 0.4

    def __init__(
        self,
        cooldown_s: Optional[float] = None,
        sustain_cycles: Optional[int] = None,
        stall_share_threshold: float = 0.3,
        linear_tolerance: float = 0.15,
        hysteresis_factor: float = 2.0,
    ):
        from dlrover_tpu.common.env import (
            brain_cooldown_s,
            brain_sustain_cycles,
        )

        self.cooldown_s = (
            brain_cooldown_s() if cooldown_s is None else cooldown_s
        )
        self.sustain_cycles = (
            brain_sustain_cycles()
            if sustain_cycles is None
            else max(int(sustain_cycles), 1)
        )
        self.stall_share_threshold = stall_share_threshold
        self.linear_tolerance = linear_tolerance
        self.hysteresis_factor = hysteresis_factor
        #: per-node consecutive-cycle streaks per signal
        self._straggler_streak: Dict[int, int] = {}
        self._hang_streak: Dict[int, int] = {}
        #: job-level chronic-stall streak
        self._stall_streak = 0
        #: cycles observed at the current world size (grow evidence)
        self._world_cycles: List[int] = [0, 0]  # [world_size, cycles]
        #: median step time per observed world size (the scaling curve)
        self._step_time_by_world: Dict[int, float] = {}
        #: hysteresis/cooldown state — journaled
        self._last: Optional[BrainDecision] = None
        self._in_flight: Optional[BrainDecision] = None
        self._next_id = 1
        #: every other ControlPlaneJournal component locks its state
        #: (the snapshot thread and the status RPC read concurrently
        #: with the brain thread's mutations); reentrant because
        #: decide() composes the locked helpers
        self._lock = threading.RLock()

    # ------------------------------------------------------------ state
    @property
    def in_flight(self) -> Optional[BrainDecision]:
        with self._lock:
            return self._in_flight

    @property
    def last_decision(self) -> Optional[BrainDecision]:
        with self._lock:
            return self._last

    def complete(self, outcome: str, now: Optional[float] = None):
        """The executor finished (or abandoned) the in-flight action:
        it becomes the cooldown anchor."""
        del outcome
        with self._lock:
            if self._in_flight is None:
                return
            done = self._in_flight
            # the cooldown runs from COMPLETION, not decision time —
            # a slow execution must not eat its own quiet period
            done.made_at = now if now is not None else time.time()
            self._last = done
            self._in_flight = None

    def _cooled_down(self, action: str, now: float) -> bool:
        if self._last is None:
            return True
        quiet = now - self._last.made_at
        needed = self.cooldown_s
        if _DIRECTION.get(action) != self._last.direction:
            needed *= self.hysteresis_factor
        return quiet >= needed

    # ---------------------------------------------------------- streaks
    def _update_streaks(self, signals: ObservatorySignals):
        world = set(signals.world)
        fenced = set(signals.fenced)
        eligible = world - fenced

        flagged = {
            n: score
            for n, score in signals.stragglers
            if n in eligible
        }
        self._straggler_streak = {
            n: self._straggler_streak.get(n, 0) + 1 for n in flagged
        }
        hung = {
            n: silence
            for n, silence in signals.hangs
            if n in eligible
        }
        self._hang_streak = {
            n: self._hang_streak.get(n, 0) + 1 for n in hung
        }
        stalled = [
            n
            for n in eligible
            if max(
                (signals.stall_shares.get(n) or {}).values(),
                default=0.0,
            )
            >= self.stall_share_threshold
        ]
        # chronic = the job is input-bound, not one unlucky node: at
        # least half the eligible world is past the share threshold
        if eligible and len(stalled) * 2 >= len(eligible):
            self._stall_streak += 1
        else:
            self._stall_streak = 0
        return flagged, hung, stalled

    def _update_history(self, signals: ObservatorySignals):
        w = len(signals.world)
        if w <= 0:
            return
        if self._world_cycles[0] != w:
            self._world_cycles = [w, 1]
        else:
            self._world_cycles[1] += 1
        if signals.median_step_time_s > 0:
            prev = self._step_time_by_world.get(w, 0.0)
            if prev <= 0:
                self._step_time_by_world[w] = signals.median_step_time_s
            else:
                a = self.HISTORY_ALPHA
                self._step_time_by_world[w] = (
                    a * signals.median_step_time_s + (1 - a) * prev
                )

    # ----------------------------------------------------------- decide
    def decide(
        self, signals: ObservatorySignals
    ) -> Optional[BrainDecision]:
        now = signals.now or time.time()
        if not signals.world:
            return None  # no completed world yet: nothing to plan
        with self._lock:
            return self._decide_locked(signals, now)

    def _decide_locked(
        self, signals: ObservatorySignals, now: float
    ) -> Optional[BrainDecision]:
        self._update_history(signals)
        flagged, hung, stalled = self._update_streaks(signals)
        if self._in_flight is not None:
            return None  # one planned action at a time
        candidate = self._drain_candidate(signals, flagged, hung)
        if candidate is None:
            candidate = self._shrink_candidate(signals, stalled)
        if candidate is None:
            candidate = self._grow_candidate(signals)
        if candidate is None:
            return None
        if not self._cooled_down(candidate.action, now):
            return None
        candidate.decision_id = self._next_id
        self._next_id += 1
        candidate.made_at = now
        candidate.goodput = signals.goodput
        self._in_flight = candidate
        # acting on a verdict consumes its streak: if the condition
        # persists after the action lands, it must re-prove itself
        self._straggler_streak.pop(candidate.node, None)
        self._hang_streak.pop(candidate.node, None)
        if candidate.action == ACTION_SHRINK:
            self._stall_streak = 0
        return candidate

    def _drain_candidate(
        self, signals: ObservatorySignals, flagged: Dict[int, float],
        hung: Dict[int, float],
    ) -> Optional[BrainDecision]:
        world = len(signals.world)
        sustained = sorted(
            (
                (score, n)
                for n, score in flagged.items()
                if self._straggler_streak.get(n, 0)
                >= self.sustain_cycles
            ),
            reverse=True,
        )
        reason = None
        if sustained:
            score, node = sustained[0]
            reason = f"straggler:{score:.2f}x"
        else:
            hung_sustained = sorted(
                (
                    (silence, n)
                    for n, silence in hung.items()
                    if self._hang_streak.get(n, 0)
                    >= self.sustain_cycles
                ),
                reverse=True,
            )
            if hung_sustained:
                silence, node = hung_sustained[0]
                reason = f"hang:{silence:.0f}s"
        if reason is None:
            return None
        to_world = world if signals.can_launch else world - 1
        if to_world < max(signals.min_nodes, 1):
            logger.warning(
                "brain: %s on node %s suppressed (world %d at "
                "min_nodes %d, no launch capacity)",
                reason, node, world, signals.min_nodes,
            )
            return None
        return BrainDecision(
            action=ACTION_DRAIN_REPLACE,
            reason=reason,
            node=node,
            from_world=world,
            to_world=to_world,
        )

    def _shrink_candidate(
        self, signals: ObservatorySignals, stalled: List[int]
    ) -> Optional[BrainDecision]:
        if self._stall_streak < self.sustain_cycles or not stalled:
            return None
        world = len(signals.world)
        if world - 1 < max(signals.min_nodes, 1):
            return None
        # victim: the worst-stalled node (ties -> highest rank, the
        # scale-down convention)
        victim = max(
            stalled,
            key=lambda n: (
                max(
                    (signals.stall_shares.get(n) or {}).values(),
                    default=0.0,
                ),
                n,
            ),
        )
        share = max(
            (signals.stall_shares.get(victim) or {}).values(),
            default=0.0,
        )
        return BrainDecision(
            action=ACTION_SHRINK,
            reason=f"data_stall:{share:.2f}",
            node=victim,
            from_world=world,
            to_world=world - 1,
        )

    def _grow_candidate(
        self, signals: ObservatorySignals
    ) -> Optional[BrainDecision]:
        world = len(signals.world)
        if not signals.can_launch or world >= signals.max_nodes:
            return None
        # only a HEALTHY job grows: any live adverse signal means new
        # capacity would feed the problem, not the throughput
        if (
            self._straggler_streak
            or self._hang_streak
            or self._stall_streak > 0
        ):
            return None
        # evidence: enough settled cycles at this size (a world change
        # resets the counter in _update_history), and the step time
        # did not degrade past tolerance when the world last grew
        # (near-linear scaling — adding a node bought real throughput)
        if self._world_cycles[1] < self.sustain_cycles:
            return None
        here = self._step_time_by_world.get(world, 0.0)
        if here <= 0:
            return None  # insufficient samples at this size
        smaller = [
            w for w in self._step_time_by_world if w < world
        ]
        if smaller:
            ref = self._step_time_by_world[max(smaller)]
            if ref > 0 and here / ref > 1.0 + self.linear_tolerance:
                return None  # scaling already sub-linear: stop
        return BrainDecision(
            action=ACTION_GROW,
            reason=f"linear_scaling:{here:.3f}s",
            node=-1,
            from_world=world,
            to_world=min(world + 1, signals.max_nodes),
        )

    # -------------------------------------------------- journal contract
    def export_state(self) -> dict:
        with self._lock:
            return self._export_locked()

    def _export_locked(self) -> dict:
        return {
            "next_id": self._next_id,
            "last": self._last.to_dict() if self._last else None,
            "in_flight": (
                self._in_flight.to_dict() if self._in_flight else None
            ),
            "straggler_streak": {
                str(k): v for k, v in self._straggler_streak.items()
            },
            "hang_streak": {
                str(k): v for k, v in self._hang_streak.items()
            },
            "stall_streak": self._stall_streak,
            "world_cycles": list(self._world_cycles),
            "step_time_by_world": {
                str(k): v
                for k, v in self._step_time_by_world.items()
            },
        }

    def restore_state(self, state: dict):
        with self._lock:
            self._restore_locked(state)

    def _restore_locked(self, state: dict):
        self._next_id = int(state.get("next_id", 1))
        last = state.get("last")
        self._last = BrainDecision.from_dict(last) if last else None
        in_flight = state.get("in_flight")
        self._in_flight = (
            BrainDecision.from_dict(in_flight) if in_flight else None
        )
        self._straggler_streak = {
            int(k): int(v)
            for k, v in (state.get("straggler_streak") or {}).items()
        }
        self._hang_streak = {
            int(k): int(v)
            for k, v in (state.get("hang_streak") or {}).items()
        }
        self._stall_streak = int(state.get("stall_streak", 0))
        cycles = state.get("world_cycles") or [0, 0]
        self._world_cycles = [int(cycles[0]), int(cycles[1])]
        self._step_time_by_world = {
            int(k): float(v)
            for k, v in (
                state.get("step_time_by_world") or {}
            ).items()
        }
