"""Resource optimization: throughput-driven scale plans.

Reference parity: ``dlrover/python/master/resource/optimizer.py``
(``ResourceOptimizer`` ABC), ``local_optimizer.py:66``
(``PSLocalOptimizer``: stage-based plans, worker-speed-ratio scaling
``:250``, OOM recovery ``:98``) and the Go Brain's
``optimize_job_worker_resource.go`` linear-throughput extrapolation.

TPU form: the unit of scaling is a whole TPU-VM worker (chips come in
fixed slices), so plans adjust *worker count* within [min, max] using
the marginal-throughput estimate from SpeedMonitor samples, plus the
OOM ladder (grow host memory for the relaunched worker).
"""

from abc import ABCMeta, abstractmethod
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.messages import ScalePlan


@dataclass
class SpeedSample:
    worker_num: int
    records_per_sec: float


class ResourceOptimizer(metaclass=ABCMeta):
    @abstractmethod
    def generate_plan(self, stage: str) -> Optional[ScalePlan]:
        ...


class JobStage:
    CREATE = "create"
    RUNNING = "running"


class LocalAllreduceOptimizer(ResourceOptimizer):
    """Worker-count optimizer from observed throughput scaling.

    Strategy (mirrors the reference's worker-speed-ratio logic): keep a
    throughput sample per world size; scale up while the marginal
    speedup of the last grow step exceeded ``min_marginal_gain`` of
    linear; scale back to the best-known size otherwise.
    """

    def __init__(
        self,
        min_workers: int = 1,
        max_workers: int = 1,
        min_marginal_gain: float = 0.6,
        oom_memory_factor: float = 1.5,
    ):
        self._min = min_workers
        self._max = max_workers
        self._gain = min_marginal_gain
        self._oom_factor = oom_memory_factor
        self._samples: Dict[int, float] = {}

    def record_speed(self, worker_num: int, records_per_sec: float):
        if worker_num <= 0 or records_per_sec <= 0:
            return
        # keep the best observed throughput per world size
        prev = self._samples.get(worker_num, 0.0)
        self._samples[worker_num] = max(prev, records_per_sec)

    def _best_known(self) -> Tuple[int, float]:
        best_n, best_v = self._min, 0.0
        for n, v in self._samples.items():
            if v > best_v:
                best_n, best_v = n, v
        return best_n, best_v

    def generate_plan(self, stage: str) -> Optional[ScalePlan]:
        if stage == JobStage.CREATE:
            plan = ScalePlan()
            plan.node_group_resources[NodeType.WORKER] = {
                "count": self._max
            }
            return plan
        if not self._samples:
            return None
        sizes = sorted(self._samples)
        current = sizes[-1]
        if len(sizes) >= 2:
            n0, n1 = sizes[-2], sizes[-1]
            v0, v1 = self._samples[n0], self._samples[n1]
            linear = v0 * n1 / n0
            marginal = (v1 - v0) / max(linear - v0, 1e-9)
            if marginal < self._gain:
                # diminishing returns: settle at the best-known size,
                # never grow further
                best_n, _ = self._best_known()
                if best_n < current:
                    plan = ScalePlan()
                    plan.node_group_resources[NodeType.WORKER] = {
                        "count": max(best_n, self._min)
                    }
                    logger.info(
                        "scale back to %d workers (marginal %.2f)",
                        best_n,
                        marginal,
                    )
                    return plan
                return None
        if current < self._max:
            plan = ScalePlan()
            plan.node_group_resources[NodeType.WORKER] = {
                "count": min(current + 1, self._max)
            }
            return plan
        return None

    def oom_recovery_plan(self, node_name: str,
                          current_memory_mb: int) -> ScalePlan:
        """Relaunch an OOM-killed worker with grown host memory
        (reference ``local_optimizer.py:98``)."""
        plan = ScalePlan()
        plan.remove_nodes.append(node_name)
        plan.launch_nodes.append(
            {
                "type": NodeType.WORKER,
                "memory": int(current_memory_mb * self._oom_factor),
            }
        )
        return plan
