"""Resource optimization: the Brain algorithm set for allreduce jobs.

Reference parity: the Go Brain service's optimizer-algorithm plugin
registry (``dlrover/go/brain/pkg/optimizer/implementation/
optalgorithm/``) restricted to the allreduce-relevant set, plus
``dlrover/python/master/resource/local_optimizer.py:66``
(``PSLocalOptimizer`` stage plans / OOM recovery ``:98`` /
worker-speed-ratio scaling ``:250``):

- worker-create        (``optimize_job_worker_create_resource.go``):
  the initial scale plan.
- worker-resource      (``optimize_job_worker_resource.go:400``):
  linear-model throughput extrapolation from SpeedMonitor samples —
  grow while the marginal speedup stays near-linear, settle back to
  the best-known world size on diminishing returns.
- worker-oom           (``optimize_job_worker_create_oom_resource.go``):
  relaunch OOM-killed workers with grown host memory.
- straggler-migrate    (``optimize_job_hot_ps_resource.go`` dual for
  allreduce): migrate nodes the network-check rounds flagged slow.

TPU form: the unit of scaling is a whole TPU-VM worker (chips come in
fixed slices), so plans adjust *worker count* within [min, max].
"""

from abc import ABCMeta, abstractmethod
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.messages import ScalePlan


@dataclass
class SpeedSample:
    worker_num: int
    records_per_sec: float


class JobStage:
    CREATE = "create"
    RUNNING = "running"


@dataclass
class JobMeta:
    """Everything an optimize algorithm may consult — the Brain's
    datastore row for one job, assembled by the auto-scaler each
    cycle."""

    stage: str = JobStage.RUNNING
    min_workers: int = 1
    max_workers: int = 1
    current_workers: int = 0
    # best observed throughput per world size (records/sec)
    speed_samples: Dict[int, float] = field(default_factory=dict)
    # node names the health-check rounds flagged as stragglers
    stragglers: List[str] = field(default_factory=list)
    # node name -> current memory MB for OOM-killed workers
    oom_nodes: Dict[str, int] = field(default_factory=dict)


class OptimizeAlgorithm(metaclass=ABCMeta):
    """One pluggable optimization rule (Brain's ``OptimizeAlgorithm``
    interface; plugins registered by name)."""

    name: str = ""

    @abstractmethod
    def optimize(self, meta: JobMeta) -> Optional[ScalePlan]:
        ...


_ALGORITHMS: Dict[str, type] = {}


def register_algorithm(cls):
    _ALGORITHMS[cls.name] = cls
    return cls


def get_algorithm(name: str) -> Optional[type]:
    return _ALGORITHMS.get(name)


@register_algorithm
class WorkerCreateResource(OptimizeAlgorithm):
    """Initial plan: launch the full worker window (elasticity shrinks
    later if throughput says so)."""

    name = "optimize_worker_create_resource"

    def optimize(self, meta: JobMeta) -> Optional[ScalePlan]:
        if meta.stage != JobStage.CREATE:
            return None
        plan = ScalePlan()
        plan.node_group_resources[NodeType.WORKER] = {
            "count": meta.max_workers
        }
        return plan


@register_algorithm
class WorkerResource(OptimizeAlgorithm):
    """Throughput-driven worker-count tuning.

    With >=3 observed world sizes the decision uses a FITTED scaling
    model extrapolated to unseen sizes (the reference Brain fits a
    linear throughput model over persisted history,
    ``optimize_job_worker_resource.go:400``): least-squares of
    ``n/speed = a + b*n`` — the Amdahl/serial-fraction form, linear in
    exactly the quantity a synchronous data-parallel job degrades in —
    then jump toward the LARGEST size whose predicted marginal gain
    still clears ``min_marginal_gain``.  The durable speed history
    (``master/datastore.py``) makes the fit meaningful across master
    restarts.

    With only 2 sizes, fall back to the local slope between them
    (the reference's worker-speed-ratio); while marginal throughput
    stays above the threshold, grow by up to 25% per cycle."""

    name = "optimize_worker_resource"

    # never jump more than this factor past the current size in one
    # plan: the fit extrapolates, reality gets a vote at each stop
    MAX_JUMP_FACTOR = 2.0

    def __init__(self, min_marginal_gain: float = 0.6,
                 growth_ratio: float = 0.25):
        self._gain = min_marginal_gain
        self._growth = growth_ratio

    def _fit_knee(self, samples: Dict[int, float],
                  max_workers: int) -> Optional[int]:
        """Fit n/speed = a + b*n; return the largest n whose predicted
        marginal gain clears the threshold (None = fit unusable)."""
        sizes = sorted(samples)
        xs = [float(n) for n in sizes]
        ys = [n / max(samples[n], 1e-9) for n in sizes]
        k = len(xs)
        sx, sy = sum(xs), sum(ys)
        sxx = sum(x * x for x in xs)
        sxy = sum(x * y for x, y in zip(xs, ys))
        denom = k * sxx - sx * sx
        if abs(denom) < 1e-12:
            return None
        b = (k * sxy - sx * sy) / denom
        a = (sy - b * sx) / k

        def speed(n: float) -> float:
            d = a + b * n
            return n / d if d > 1e-12 else 0.0

        if b <= 0:
            # no measurable serial fraction yet: predicted scaling is
            # (super)linear — the knee is past max_workers
            return max_workers
        best = None
        for n in range(1, max_workers + 1):
            per_worker = speed(n) / n
            marginal = (speed(n + 1) - speed(n)) / max(
                per_worker, 1e-12
            )
            if marginal >= self._gain:
                best = n + 1
        return best

    @staticmethod
    def _best_known(meta: JobMeta, tolerance: float = 0.05) -> int:
        """The SMALLEST size within ``tolerance`` of the peak observed
        throughput — scale-back exists to shed workers that buy almost
        nothing, so near-ties resolve to fewer workers."""
        if not meta.speed_samples:
            return meta.min_workers
        peak = max(meta.speed_samples.values())
        ok = [
            n
            for n, v in meta.speed_samples.items()
            if v >= (1.0 - tolerance) * peak
        ]
        return min(ok) if ok else meta.min_workers

    def optimize(self, meta: JobMeta) -> Optional[ScalePlan]:
        if meta.stage != JobStage.RUNNING:
            return None
        samples = meta.speed_samples
        if not samples:
            return None
        sizes = sorted(samples)
        # the ACTUAL world size, not the max-ever-sampled one: after a
        # settle the stale larger sample would otherwise re-emit the
        # same scale-back plan every cycle forever
        current = meta.current_workers or sizes[-1]
        if len(sizes) >= 3:
            target = self._fit_knee(samples, meta.max_workers)
            if target is not None:
                target = max(target, meta.min_workers)
                if target > current:
                    cap = int(current * self.MAX_JUMP_FACTOR)
                    count = min(target, cap, meta.max_workers)
                    if count == current:
                        return None  # capped at where we already are
                    plan = ScalePlan()
                    plan.node_group_resources[NodeType.WORKER] = {
                        "count": count
                    }
                    logger.info(
                        "fitted scaling model: knee at %d workers "
                        "(current %d)", target, current,
                    )
                    return plan
                if target < current:
                    settle = max(
                        min(target, self._best_known(meta)),
                        meta.min_workers,
                    )
                    if settle != current:
                        plan = ScalePlan()
                        plan.node_group_resources[
                            NodeType.WORKER
                        ] = {"count": settle}
                        logger.info(
                            "fitted scaling model: settling at %d "
                            "workers (current %d)", settle, current,
                        )
                        return plan
                return None  # already at the predicted knee
        if len(sizes) >= 2:
            # stop/settle decision uses the LOCAL slope between the two
            # largest observed sizes (the reference's worker-speed-ratio
            # compares speed before/after the last grow step) — a global
            # least-squares fit smooths the knee away and keeps growing
            # past it
            n0, n1 = sizes[-2], sizes[-1]
            local_slope = (samples[n1] - samples[n0]) / (n1 - n0)
            ref = current if current in samples else n1
            per_worker_now = samples[ref] / ref
            # marginal value of one more worker, as a fraction of the
            # current per-worker throughput (1.0 == perfectly linear)
            marginal = local_slope / max(per_worker_now, 1e-9)
            if marginal < self._gain:
                best_n = self._best_known(meta)
                if best_n != current:
                    plan = ScalePlan()
                    plan.node_group_resources[NodeType.WORKER] = {
                        "count": max(best_n, meta.min_workers)
                    }
                    logger.info(
                        "scale back to %d workers (marginal %.2f)",
                        best_n, marginal,
                    )
                    return plan
                return None  # diminishing returns: stop growing
        if current < meta.max_workers:
            step = max(1, int(current * self._growth))
            plan = ScalePlan()
            plan.node_group_resources[NodeType.WORKER] = {
                "count": min(current + step, meta.max_workers)
            }
            return plan
        return None


@register_algorithm
class WorkerOomResource(OptimizeAlgorithm):
    """Relaunch OOM-killed workers with grown host memory
    (reference ``local_optimizer.py:98`` OOM ladder)."""

    name = "optimize_worker_oom_resource"

    def __init__(self, oom_memory_factor: float = 1.5):
        self._factor = oom_memory_factor

    def optimize(self, meta: JobMeta) -> Optional[ScalePlan]:
        if not meta.oom_nodes:
            return None
        plan = ScalePlan()
        for node, memory_mb in meta.oom_nodes.items():
            plan.remove_nodes.append(node)
            plan.launch_nodes.append(
                {
                    "type": NodeType.WORKER,
                    "memory": int(memory_mb * self._factor),
                }
            )
        return plan


@register_algorithm
class StragglerMigrate(OptimizeAlgorithm):
    """Migrate nodes the health-check rounds flagged slow (the
    allreduce dual of hot-PS migration: a synchronous mesh runs at the
    slowest node's speed, so one straggler taxes the whole job)."""

    name = "optimize_straggler_migrate"

    def optimize(self, meta: JobMeta) -> Optional[ScalePlan]:
        if not meta.stragglers:
            return None
        plan = ScalePlan()
        for node in meta.stragglers:
            plan.migrate_nodes[str(node)] = {"type": NodeType.WORKER}
        return plan


def merge_plans(plans: List[Optional[ScalePlan]]) -> Optional[ScalePlan]:
    merged = ScalePlan()
    for p in plans:
        if p is None:
            continue
        merged.node_group_resources.update(p.node_group_resources)
        merged.launch_nodes.extend(p.launch_nodes)
        merged.remove_nodes.extend(p.remove_nodes)
        merged.migrate_nodes.update(p.migrate_nodes)
    return None if merged.is_empty() else merged


class ResourceOptimizer(metaclass=ABCMeta):
    @abstractmethod
    def generate_plan(self, stage: str) -> Optional[ScalePlan]:
        ...


class LocalAllreduceOptimizer(ResourceOptimizer):
    """The local Brain: runs the registered algorithm set against the
    job's observed state (reference ``BrainResoureOptimizer`` role
    without the external service; same algorithms, in-process)."""

    def __init__(
        self,
        min_workers: int = 1,
        max_workers: int = 1,
        min_marginal_gain: float = 0.6,
        oom_memory_factor: float = 1.5,
        datastore=None,
        job_name: str = "default",
    ):
        """``datastore``/``job_name``: persist speed samples per job
        (reference: the Go Brain's MySQL job-metrics recorders) so a
        restarted master's WorkerResource decisions start from the
        job's full observed speed curve, not an empty map.  Defaults
        to the process datastore when ``DLROVER_TPU_BRAIN_DB`` is
        set."""
        if datastore is None:
            from dlrover_tpu.master.datastore import (
                get_default_datastore,
            )

            datastore = get_default_datastore()
        self._datastore = datastore
        self._job_name = job_name
        self._min = min_workers
        self._max = max_workers
        self._samples: Dict[int, float] = {}
        if datastore is not None:
            try:
                self._samples.update(
                    datastore.speed_history(job_name)
                )
            except Exception as e:  # noqa: BLE001
                logger.warning(
                    "speed-history restore failed: %s", e
                )
        self._current_workers = 0
        self._stragglers: List[str] = []
        self._oom_nodes: Dict[str, int] = {}
        self._algorithms: List[OptimizeAlgorithm] = [
            WorkerCreateResource(),
            WorkerResource(min_marginal_gain=min_marginal_gain),
            WorkerOomResource(oom_memory_factor=oom_memory_factor),
            StragglerMigrate(),
        ]
        self._oom_factor = oom_memory_factor

    # -- observation feeds (the Brain's datastore writes) ---------------
    def record_speed(self, worker_num: int, records_per_sec: float):
        if worker_num <= 0 or records_per_sec <= 0:
            return
        prev = self._samples.get(worker_num, 0.0)
        self._samples[worker_num] = max(prev, records_per_sec)
        self._current_workers = worker_num
        if self._datastore is not None and records_per_sec > prev:
            try:
                self._datastore.record_speed(
                    self._job_name, worker_num, records_per_sec
                )
            except Exception as e:  # noqa: BLE001
                logger.warning("speed persist failed: %s", e)

    def set_current_workers(self, worker_num: int):
        if worker_num > 0:
            self._current_workers = worker_num

    def report_stragglers(self, nodes: List[str]):
        self._stragglers = list(nodes)

    def report_oom(self, node_name: str, current_memory_mb: int):
        self._oom_nodes[node_name] = current_memory_mb

    # -- plan generation -------------------------------------------------
    def _meta(self, stage: str) -> JobMeta:
        sizes = sorted(self._samples)
        return JobMeta(
            stage=stage,
            min_workers=self._min,
            max_workers=self._max,
            current_workers=self._current_workers
            or (sizes[-1] if sizes else 0),
            speed_samples=dict(self._samples),
            stragglers=list(self._stragglers),
            oom_nodes=dict(self._oom_nodes),
        )

    def generate_plan(self, stage: str) -> Optional[ScalePlan]:
        meta = self._meta(stage)
        plans = [alg.optimize(meta) for alg in self._algorithms]
        plan = merge_plans(plans)
        # one-shot signals are consumed by the plan they produced
        self._stragglers = []
        self._oom_nodes = {}
        return plan

    def oom_recovery_plan(self, node_name: str,
                          current_memory_mb: int) -> ScalePlan:
        """Immediate OOM relaunch plan (outside the periodic cycle)."""
        self.report_oom(node_name, current_memory_mb)
        meta = self._meta(JobStage.RUNNING)
        plan = WorkerOomResource(self._oom_factor).optimize(meta)
        self._oom_nodes = {}
        return plan
