"""The Brain's execution arm: planned actions, not emergent restarts.

A :class:`~dlrover_tpu.master.resource_optimizer.BrainDecision` names
a world transition; this module turns it into ONE coordinated action
built from the pieces PRs 7-9 already proved:

- **drain_replace / shrink**: post a cooperative ``drain`` directive
  for the target node (:class:`NodeDirectives`, delivered piggybacked
  on the agent's monitor-pacing ``WaitingNodeNum`` poll — zero extra
  RPCs).  The agent runs the PR-9 graceful-drain protocol: SIGUSR1
  snapshot-every-step → flush → ``node_preempted`` report (which
  fences the node at the rendezvous manager) → exit with the
  preemption code.  Survivors' long-polls wake within one monitor
  interval, re-rendezvous without the node, ``solver.resolve_for_world``
  re-solves the mesh and the reshard-aware restore resumes from the
  drained step — never a restart-from-scratch.  When a scaler is
  attached (k8s), the replacement pod is launched through it in the
  same action.
- **grow**: a worker-count :class:`ScalePlan` through the scaler (the
  new pod joins the rendezvous; the window rule + elastic re-mesh do
  the rest).  Without a scaler there is no launch capacity and the
  optimizer never emits grow.

Execution is ASYNCHRONOUS: ``begin`` fires the action, the
auto-scaler polls ``check`` each cycle until the world reflects it,
and ``force`` is the deadline fallback — a node that never picked up
its directive (dead agent, wedged monitor loop) is fenced
master-side so survivors re-mesh anyway, and a grow whose pod never
arrived is abandoned.  Both outcomes are journaled, so a failed-over
master resumes or abandons instead of flip-flopping.
"""

import threading
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.messages import ScalePlan
from dlrover_tpu.master.resource_optimizer import (
    ACTION_DRAIN_REPLACE,
    ACTION_GROW,
    ACTION_SHRINK,
    OUTCOME_ABANDONED,
    OUTCOME_DONE,
    OUTCOME_FENCED_FALLBACK,
    BrainDecision,
)

#: the cooperative directive verb the agent understands
DIRECTIVE_DRAIN = "drain"


class NodeDirectives:
    """Pending per-node directives, consumed on delivery.

    One slot per node: the Brain issues one planned action at a time,
    so a second post for the same node replaces the first (same
    decision resumed after a failover keeps its id)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pending: Dict[int, Tuple[str, str, int]] = {}

    def post(self, node_rank: int, action: str, reason: str,
             decision_id: int):
        with self._lock:
            self._pending[int(node_rank)] = (
                action, reason, int(decision_id)
            )

    def take(self, node_rank: int) -> Optional[Tuple[str, str, int]]:
        """Consume the node's pending directive (the delivery)."""
        with self._lock:
            return self._pending.pop(int(node_rank), None)

    def peek(self, node_rank: int) -> Optional[Tuple[str, str, int]]:
        with self._lock:
            return self._pending.get(int(node_rank))

    def clear(self, node_rank: int):
        with self._lock:
            self._pending.pop(int(node_rank), None)

    def pending_nodes(self) -> List[int]:
        with self._lock:
            return sorted(self._pending)


class BrainExecutor:
    """Executes one :class:`BrainDecision` against the live job."""

    def __init__(self, rdzv_manager=None, directives=None,
                 job_manager=None, scaler=None):
        self._rdzv = rdzv_manager
        self.directives = directives or NodeDirectives()
        self._job_manager = job_manager
        self._scaler = scaler
        #: decision ids whose pod-side follow-up already ran
        self._followed_up = set()

    def set_scaler(self, scaler):
        self._scaler = scaler

    @property
    def can_launch(self) -> bool:
        """Whether this master can CREATE nodes (grow / replace)."""
        return self._scaler is not None

    # ------------------------------------------------------------ world
    def current_world(self) -> List[int]:
        if self._rdzv is None:
            return []
        return self._rdzv.current_world_ranks()

    def fenced(self) -> List[int]:
        if self._rdzv is None:
            return []
        return self._rdzv.fenced_ranks()

    def world_bounds(self) -> Tuple[int, int]:
        """(min_nodes, max_nodes) from the live rendezvous params."""
        if self._rdzv is None:
            return 1, 1
        params = self._rdzv.rdzv_params
        return params.min_nodes, params.max_nodes

    def _node_name(self, node_rank: int) -> Optional[str]:
        """rank -> pod name for scaler-side removal/migration (the
        seed mapping; an unmapped rank just skips the scaler leg —
        the cooperative directive still drains it)."""
        if self._job_manager is None:
            return None
        for node in self._job_manager.get_running_nodes():
            key = (
                node.rank_index
                if node.rank_index is not None
                else node.id
            )
            if key == node_rank and node.name:
                return node.name
        return None

    # ---------------------------------------------------------- execute
    def begin(self, decision: BrainDecision):
        """Fire the action (non-blocking).  Drains post ONLY the
        cooperative directive here: deleting the pod through the
        scaler in the same breath would SIGTERM the agent before the
        directive's next-poll delivery, collapsing the graceful drain
        into the pod's termination grace — the pod-side leg runs as a
        follow-up once the node is fenced/out of the world (or from
        ``force`` when it never cooperates)."""
        if decision.action in (ACTION_DRAIN_REPLACE, ACTION_SHRINK):
            self.directives.post(
                decision.node,
                DIRECTIVE_DRAIN,
                decision.reason,
                decision.decision_id,
            )
        elif decision.action == ACTION_GROW:
            plan = ScalePlan()
            plan.node_group_resources[NodeType.WORKER] = {
                "count": decision.to_world
            }
            self._scale(plan)

    def _scaler_followup(self, decision: BrainDecision):
        """Pod-side leg of a drain, AFTER the drain concluded: delete
        the (already exiting) pod, plus a replacement when the
        decision planned one.  Idempotence guard: a resumed check and
        the original completion must not double-create pods."""
        if self._scaler is None:
            return
        if decision.decision_id in self._followed_up:
            return
        self._followed_up.add(decision.decision_id)
        name = self._node_name(decision.node)
        if name is None:
            return
        plan = ScalePlan()
        if decision.action == ACTION_DRAIN_REPLACE and (
            decision.to_world >= decision.from_world
        ):
            # replace: a fresh pod for the drained one
            plan.migrate_nodes[name] = {"type": NodeType.WORKER}
        else:
            plan.remove_nodes.append(name)
        self._scale(plan)

    def _scale(self, plan: ScalePlan):
        if self._scaler is None:
            return
        try:
            self._scaler.scale(plan)
        except Exception as e:  # noqa: BLE001 - the directive path
            # still drains; the pod-side leg is best-effort
            logger.warning("brain scaler leg failed: %s", e)

    def check(self, decision: BrainDecision) -> Optional[str]:
        """Poll for completion; an outcome string once the world
        reflects the decision, None while still pending."""
        world = self.current_world()
        if decision.action in (ACTION_DRAIN_REPLACE, ACTION_SHRINK):
            if decision.node in self.fenced() or (
                world and decision.node not in world
            ):
                self._scaler_followup(decision)
                return OUTCOME_DONE
            return None
        if decision.action == ACTION_GROW:
            if len(world) >= decision.to_world:
                return OUTCOME_DONE
            return None
        return OUTCOME_DONE  # unknown action: nothing to wait for

    def force(self, decision: BrainDecision) -> str:
        """Deadline fallback: make the decision safe without the
        cooperating party."""
        if decision.action in (ACTION_DRAIN_REPLACE, ACTION_SHRINK):
            # the node never picked its directive up (dead / wedged
            # agent): fence it master-side so survivors re-mesh away
            # from it; its own teardown is the job manager's problem
            self.directives.clear(decision.node)
            if self._rdzv is not None:
                self._rdzv.fence_node(decision.node)
            # the node isn't cooperating: deleting its pod (SIGTERM →
            # the agent's own drain handler, bounded by the pod
            # grace) is exactly the right escalation here
            self._scaler_followup(decision)
            logger.warning(
                "brain: node %s never acked drain (decision %s); "
                "fenced master-side", decision.node,
                decision.decision_id,
            )
            return OUTCOME_FENCED_FALLBACK
        return OUTCOME_ABANDONED

    def resume(self, decision: BrainDecision) -> bool:
        """Re-arm an in-flight action inherited from a dead master
        incarnation (directives are memory-only and died with it).
        Returns False when the decision is already satisfied."""
        # the pod-side follow-up may have run on the dead incarnation
        # with its journal record still in the write-behind linger —
        # a resumed drain therefore NEVER re-runs it (same reasoning
        # as grow below: re-issuing risks double-created pods; a
        # missing replacement is the controller's to reconcile)
        self._followed_up.add(decision.decision_id)
        if self.check(decision) is not None:
            return False
        if decision.action in (ACTION_DRAIN_REPLACE, ACTION_SHRINK):
            self.directives.post(
                decision.node,
                DIRECTIVE_DRAIN,
                decision.reason,
                decision.decision_id,
            )
        # grow: the plan was already handed to the scaler/operator
        # pre-crash; re-issuing would double-create — just keep
        # waiting for the world (the deadline abandons it otherwise)
        return True


def execution_deadline_s(interval_s: float) -> float:
    """How long an in-flight action may stay pending before ``force``:
    generous multiples of the decision cadence, floored
    (``DLROVER_TPU_BRAIN_EXEC_DEADLINE_S``) so a tight chaos interval
    still leaves room for a real drain + re-mesh."""
    from dlrover_tpu.common.env import env_float

    return max(
        8.0 * interval_s,
        env_float("DLROVER_TPU_BRAIN_EXEC_DEADLINE_S", 20.0),
    )
