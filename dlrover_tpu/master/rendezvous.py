"""Master-side rendezvous managers.

Reference parity: ``dlrover/python/master/elastic_training/rdzv_manager.py``
(RendezvousManager ABC ``:58``, min/max/node-unit window ``:135``,
``join_rendezvous:213``, ``num_nodes_waiting:272`` — the restart signal,
ckpt-step barrier ``sync_ckpt_nodes:295``; ElasticTrainingRendezvousManager
``:329``; NetworkCheckRendezvousManager ``:390`` with pairwise group
shuffling for straggler/fault isolation).

TPU notes: a "node" is a TPU-VM worker; ``local_world_size`` is its
training-process count (usually 1 process driving N chips).  The
completed world is what the agent feeds into
``jax.distributed.initialize`` (process_id = rank in this world).
"""

import math
import threading
import time
from abc import ABCMeta
from typing import Callable, Dict, List, Optional, Tuple

from dlrover_tpu.common.constants import NetworkFailureReason
from dlrover_tpu.common.fault_injection import maybe_crash
from dlrover_tpu.common.log import default_logger as logger


class RendezvousParameters:
    def __init__(self, min_nodes: int = 1, max_nodes: int = 1,
                 waiting_timeout: float = 30.0, node_unit: int = 1,
                 join_timeout: float = 600.0):
        self.min_nodes = min_nodes
        self.max_nodes = max_nodes
        self.waiting_timeout = waiting_timeout
        self.node_unit = node_unit
        self.join_timeout = join_timeout


class RendezvousManager(metaclass=ABCMeta):
    #: long-poll wake slice: rendezvous completion is partly
    #: TIME-driven (the waiting_timeout window rule), so a parked
    #: waiter re-evaluates at this cadence even without a notify —
    #: server-side CPU only, zero RPCs
    WAIT_SLICE_S = 0.2

    def __init__(self):
        # a Condition IS a lock for ``with`` purposes; every mutation
        # notifies so long-poll waiters (comm world / waiting count)
        # wake on the event instead of the client re-polling over RPC
        self._lock = threading.Condition()
        self._name = ""
        self._waiting_nodes: Dict[int, int] = {}  # rank -> local_world_size
        self._rdzv_nodes: Dict[int, int] = {}
        self._lastcall_time = 0.0
        self._rdzv_params = RendezvousParameters()
        self._rdzv_round = 0
        self._node_unit = 1
        self._start_rdzv_time = 0.0
        self._latest_rdzv_nodes: List[int] = []
        self._ckpt_steps: Dict[int, int] = {}
        # node_rank -> interconnect hierarchy labels (outermost first);
        # fed by NodeTopology reports, consumed at round completion
        self._node_topology: Dict[int, tuple] = {}
        #: bumped on every state change (join/remove/params/round
        #: completion); the ``CommWorld`` delta protocol's version
        self._version = 0
        #: preemption-fenced nodes: rank -> fence expiry (epoch s).
        #: A fenced node is excluded from round completion (its
        #: hardware is going away); the fence expires so a re-created
        #: pod with the same rank can rejoin later.
        self._fenced: Dict[int, float] = {}
        #: a member of the live world was fenced: survivors must
        #: re-mesh even though nobody is WAITING yet — this makes
        #: ``num_nodes_waiting`` signal the membership change within
        #: one monitor interval of the preemption notice instead of
        #: after the dead node's heartbeat goes stale
        self._pending_remesh = False
        #: failover journal hook: ``cb(op, args)``; rendezvous state is
        #: tiny, so every mutation journals the FULL state dict —
        #: replay is last-writer-wins and therefore idempotent, and a
        #: restarted master resumes the same round at the same version
        self._journal_cb: Optional[Callable[[str, dict], None]] = None

    def set_journal(self, cb: Optional[Callable[[str, dict], None]]):
        with self._lock:
            self._journal_cb = cb

    def _journal_locked(self):
        """Caller holds the lock: journal the full current state."""
        if self._journal_cb is not None:
            try:
                self._journal_cb("state", self._export_locked())
            except Exception as e:  # noqa: BLE001
                logger.warning("rendezvous journal failed: %s", e)

    def _mutated(self):
        """Caller holds the lock: version-stamp the change, wake
        long-poll waiters, journal the new state."""
        self._version += 1
        self._lock.notify_all()
        self._journal_locked()

    @property
    def state_version(self) -> int:
        with self._lock:
            return self._version

    @property
    def rdzv_params(self) -> RendezvousParameters:
        """The live window parameters (the agents' ``--nnodes`` min/max
        land here via ``report_rdzv_params``) — the Brain's world
        clamps read them instead of guessing from ``--node_num``."""
        with self._lock:
            params = self._rdzv_params
            return RendezvousParameters(
                min_nodes=params.min_nodes,
                max_nodes=params.max_nodes,
                waiting_timeout=params.waiting_timeout,
                node_unit=self._node_unit,
            )

    def current_world_ranks(self) -> List[int]:
        """Node ranks of the latest COMPLETED round — the world the
        Brain plans against (insertion order = rank order)."""
        with self._lock:
            return list(self._latest_rdzv_nodes)

    def fenced_ranks(self) -> List[int]:
        """Live (unexpired) preemption fences — the Brain must not
        re-plan a node that is already on its way out."""
        with self._lock:
            return sorted(self._live_fenced_locked().keys())

    def set_node_topology(self, node_rank: int, levels: tuple):
        with self._lock:
            self._node_topology[node_rank] = tuple(levels)
            # journaled (ranks sort by topology after replay) but NOT
            # version-bumped: topology is advisory, not world state
            self._journal_locked()

    def _topology_order(self, ranks: List[int]) -> List[int]:
        """Caller holds the lock."""
        if not self._node_topology:
            return ranks
        from dlrover_tpu.master.net_topology import order_by_topology

        return order_by_topology(ranks, self._node_topology)

    def update_rdzv_params(self, min_nodes: int, max_nodes: int,
                           waiting_timeout: float, node_unit: int):
        with self._lock:
            self._rdzv_params.min_nodes = min_nodes
            self._rdzv_params.max_nodes = max_nodes
            self._rdzv_params.waiting_timeout = waiting_timeout
            self._node_unit = max(node_unit, 1)
            self._mutated()
            logger.info(
                "%s rdzv params: min=%s max=%s timeout=%s unit=%s",
                self._name, min_nodes, max_nodes, waiting_timeout, node_unit,
            )

    def get_rdzv_round(self) -> int:
        return self._rdzv_round

    def add_alive_node(self, node_rank: int):
        pass

    def remove_alive_node(self, node_rank: int):
        """Drop a dead node from the pending rendezvous."""
        with self._lock:
            if node_rank in self._waiting_nodes:
                del self._waiting_nodes[node_rank]
                self._mutated()
                logger.info(
                    "%s: removed dead node %s from waiting list",
                    self._name, node_rank,
                )

    def _live_fenced_locked(self) -> Dict[int, float]:
        """Caller holds the lock: prune expired fences, return live."""
        now = time.time()
        expired = [r for r, t in self._fenced.items() if t <= now]
        for r in expired:
            del self._fenced[r]
        return self._fenced

    def fence_node(self, node_rank: int,
                   ttl_s: Optional[float] = None):
        """Preemption fencing: the node reported it is about to die
        (graceful drain done).  Drop it from the pending round,
        exclude it from completions until the fence expires, and —
        when it was part of the live world — raise the pending-remesh
        flag so survivors' waiting-count long-polls wake NOW."""
        if ttl_s is None:
            from dlrover_tpu.common.env import env_float

            ttl_s = env_float("DLROVER_TPU_FENCE_TTL_S", 30.0)
        with self._lock:
            self._fenced[node_rank] = time.time() + max(ttl_s, 0.0)
            self._waiting_nodes.pop(node_rank, None)
            if node_rank in self._latest_rdzv_nodes:
                self._pending_remesh = True
            self._mutated()
            logger.info(
                "%s: fenced node %s for %.0fs (pending_remesh=%s)",
                self._name, node_rank, ttl_s, self._pending_remesh,
            )

    def join_rendezvous(self, node_rank: int, local_world_size: int) -> int:
        with self._lock:
            if not self._waiting_nodes:
                self._start_rdzv_time = time.time()
            # a join IS liveness: a re-created pod re-announcing
            # itself clears its own fence
            self._fenced.pop(node_rank, None)
            self._waiting_nodes[node_rank] = local_world_size
            self._rdzv_nodes = {}
            self._lastcall_time = time.time()
            self._mutated()
        # chaos hook: the join is journaled but the round is pending —
        # a kill pinned here proves a restarted master resumes the
        # SAME round with the already-joined members
        maybe_crash("mid_rendezvous")
        return self._rdzv_round

    def _check_rdzv_completed(self) -> bool:
        """Caller holds the lock.  The window rule (reference ``:135``):
        complete immediately at max_nodes; after waiting_timeout complete
        with the largest multiple of node_unit >= min_nodes."""
        fenced = self._live_fenced_locked()
        eligible = {
            r: v
            for r, v in self._waiting_nodes.items()
            if r not in fenced
        }
        waiting = len(eligible)
        params = self._rdzv_params
        if waiting == params.max_nodes:
            completed = True
        else:
            over_min = (
                waiting >= params.min_nodes
                and waiting >= self._node_unit
            )
            timed_out = (
                self._lastcall_time > 0
                and time.time() - self._lastcall_time
                >= params.waiting_timeout
            )
            completed = over_min and timed_out
        if completed:
            # round down to a node_unit multiple; excess nodes STAY in
            # the waiting list so they keep signalling a pending
            # re-rendezvous instead of being stranded
            usable = (waiting // self._node_unit) * self._node_unit
            usable = min(usable, self._rdzv_params.max_nodes)
            ranks = sorted(eligible.keys())[:usable]
            # topology-aware ordering: neighbors on the interconnect
            # get adjacent global ranks (the world dict's insertion
            # order IS the rank order the agents apply); numeric order
            # when no topology was reported
            ranks = self._topology_order(ranks)
            self._rdzv_nodes = {
                r: self._waiting_nodes[r] for r in ranks
            }
            self._latest_rdzv_nodes = list(self._rdzv_nodes.keys())
            for r in ranks:
                del self._waiting_nodes[r]
            self._lastcall_time = 0.0
            self._rdzv_round += 1
            self._ckpt_steps = {}  # new world: reset the ckpt barrier
            # the re-mesh the fence demanded has happened
            self._pending_remesh = False
            self._mutated()
            logger.info(
                "%s rendezvous round %s completed with %s nodes",
                self._name, self._rdzv_round, len(self._rdzv_nodes),
            )
        return completed

    def get_comm_world(self, node_rank: int) -> Tuple[int, int, Dict[int, int]]:
        """Return (round, group, world).  Empty world while pending."""
        with self._lock:
            if not self._rdzv_nodes:
                self._check_rdzv_completed()
            if self._rdzv_nodes:
                return self._rdzv_round, 0, dict(self._rdzv_nodes)
            return self._rdzv_round, 0, {}

    def get_comm_world_versioned(
        self, node_rank: int
    ) -> Tuple[int, int, Dict[int, int], int]:
        """``get_comm_world`` plus the matching state version, read
        atomically (the Condition's lock is reentrant, so the bump a
        lazy round-completion performs inside ``get_comm_world`` is
        visible in the version returned WITH that world)."""
        with self._lock:
            rnd, group, world = self.get_comm_world(node_rank)
            return rnd, group, world, self._version

    def wait_comm_world(
        self, node_rank: int, version: int = -1, timeout: float = 0.0
    ) -> Tuple[int, int, Dict[int, int], int]:
        """Long-poll ``get_comm_world``: block until the world is
        complete AND the state version moved past the caller's cached
        ``version`` (or ``timeout`` elapses); returns
        ``(round, group, world, version)``."""
        deadline = time.monotonic() + max(timeout, 0.0)
        while True:
            rnd, group, world, current = (
                self.get_comm_world_versioned(node_rank)
            )
            if world and (version < 0 or current != version):
                return rnd, group, world, current
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return rnd, group, world, current
            with self._lock:
                # completion can be time-driven (the window rule), so
                # cap the park and re-evaluate
                self._lock.wait(min(remaining, self.WAIT_SLICE_S))

    def wait_num_nodes(
        self, last_num: int = -1, timeout: float = 0.0
    ) -> int:
        """Long-poll ``num_nodes_waiting``: block until the (gated)
        waiting count differs from the caller's ``last_num`` or the
        timeout elapses."""
        deadline = time.monotonic() + max(timeout, 0.0)
        while True:
            waiting = self.num_nodes_waiting()
            if last_num < 0 or waiting != last_num:
                return waiting
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return waiting
            with self._lock:
                self._lock.wait(min(remaining, self.WAIT_SLICE_S))

    def num_nodes_waiting(self) -> int:
        """Nonzero once a new rendezvous is pending — the running agents
        poll this to learn that a restart/re-mesh is required.

        Gated (reference ``:272-285``): leftover sub-node_unit nodes
        alone must NOT signal a restart (they cannot change the world),
        or every completed round with a remainder would trigger an
        infinite restart storm.  A re-joining member of the latest world
        always signals (its training process died).  A PENDING REMESH
        (a live-world member was preemption-fenced) signals even with
        an empty waiting list: the survivors must re-rendezvous away
        from the dying node, and they learn it from this count."""
        with self._lock:
            if not self._waiting_nodes:
                return self._node_unit if self._pending_remesh else 0
            rejoined = any(
                r in self._latest_rdzv_nodes
                for r in self._waiting_nodes
            )
            if (
                rejoined
                or self._pending_remesh
                or len(self._waiting_nodes) >= self._node_unit
            ):
                return max(
                    len(self._waiting_nodes),
                    self._node_unit if self._pending_remesh else 0,
                )
            return 0

    def sync_ckpt_nodes(self, node_id: int, step: int) -> bool:
        """Barrier: all latest-rendezvous nodes report the same in-memory
        checkpoint step (reference ``:295``)."""
        with self._lock:
            self._ckpt_steps[node_id] = step
            steps = set(self._ckpt_steps.values())
            if len(steps) > 1:
                return False
            return len(self._ckpt_steps) == len(self._latest_rdzv_nodes)

    # --------------------------------------------- failover replay
    def _export_locked(self) -> dict:
        """Caller holds the lock: JSON-safe full state (int dict keys
        become strings on the wire; restore converts them back)."""
        state = {
            "waiting": dict(self._waiting_nodes),
            "rdzv_nodes": dict(self._rdzv_nodes),
            "round": self._rdzv_round,
            "latest": list(self._latest_rdzv_nodes),
            "ckpt_steps": dict(self._ckpt_steps),
            "topology": {
                str(r): list(v)
                for r, v in self._node_topology.items()
            },
            "params": [
                self._rdzv_params.min_nodes,
                self._rdzv_params.max_nodes,
                self._rdzv_params.waiting_timeout,
                self._node_unit,
            ],
            "lastcall": self._lastcall_time,
            "version": self._version,
            "fenced": {
                str(r): float(t) for r, t in self._fenced.items()
            },
            "pending_remesh": self._pending_remesh,
        }
        state.update(self._export_extra_locked())
        return state

    def _export_extra_locked(self) -> dict:
        """Subclass state rider (network-check verdicts etc.)."""
        return {}

    def _restore_extra_locked(self, state: dict):
        pass

    def export_state(self) -> dict:
        with self._lock:
            return self._export_locked()

    def restore_state(self, state: dict):
        """Install a journaled/snapshotted state (replay path — not
        re-journaled).  The version is restored as-is so pre-crash
        clients' ``NotModified`` caches stay coherent with the new
        incarnation."""
        with self._lock:
            self._waiting_nodes = {
                int(k): int(v)
                for k, v in (state.get("waiting") or {}).items()
            }
            self._rdzv_nodes = {
                int(k): int(v)
                for k, v in (state.get("rdzv_nodes") or {}).items()
            }
            self._rdzv_round = int(state.get("round", 0))
            self._latest_rdzv_nodes = [
                int(r) for r in (state.get("latest") or [])
            ]
            self._ckpt_steps = {
                int(k): int(v)
                for k, v in (state.get("ckpt_steps") or {}).items()
            }
            self._node_topology = {
                int(k): tuple(v)
                for k, v in (state.get("topology") or {}).items()
            }
            params = state.get("params")
            if params:
                self._rdzv_params.min_nodes = int(params[0])
                self._rdzv_params.max_nodes = int(params[1])
                self._rdzv_params.waiting_timeout = float(params[2])
                self._node_unit = max(int(params[3]), 1)
            # the window rule is time-driven: restart the waiting
            # window NOW so a pending round can't complete instantly
            # off a stale pre-crash timestamp (members that died with
            # the master re-join and re-arm it anyway)
            if self._waiting_nodes and state.get("lastcall"):
                self._lastcall_time = time.time()
            self._fenced = {
                int(k): float(v)
                for k, v in (state.get("fenced") or {}).items()
            }
            self._pending_remesh = bool(
                state.get("pending_remesh", False)
            )
            self._restore_extra_locked(state)
            self._version = max(
                self._version, int(state.get("version", 0))
            )
            self._lock.notify_all()


class ElasticTrainingRendezvousManager(RendezvousManager):
    def __init__(self):
        super().__init__()
        self._name = "elastic-training"


class NetworkCheckRendezvousManager(RendezvousManager):
    """Pairwise health-check rendezvous (reference ``:390``).

    Nodes are split into groups of 2 that run a matmul+collective
    payload; a second round re-pairs suspect nodes with known-good ones
    so a bad chip/link is isolated to a single node.
    """

    GROUP_SIZE = 2

    def __init__(self):
        super().__init__()
        self._name = "network-check"
        self._node_status: Dict[int, bool] = {}
        self._node_times: Dict[int, float] = {}
        self._check_round = 0
        self._node_groups: List[Dict[int, int]] = []
        self._fault_nodes: List[int] = []
        self._straggler_ratio = 3.0

    def join_rendezvous(self, node_rank: int, local_world_size: int) -> int:
        with self._lock:
            if not self._waiting_nodes:
                self._start_rdzv_time = time.time()
                self._node_groups = []
                if self._check_round >= 2:
                    # a fresh sweep after a completed 2-round check:
                    # stale verdicts from the previous sweep must not
                    # leak (a then-healthy node may be broken now)
                    self._node_status = {}
                    self._node_times = {}
                    self._check_round = 0
            self._waiting_nodes[node_rank] = local_world_size
            self._rdzv_nodes = {}
            self._lastcall_time = time.time()
            self._mutated()
        return self._rdzv_round

    def get_comm_world(self, node_rank: int) -> Tuple[int, int, Dict[int, int]]:
        with self._lock:
            if not self._rdzv_nodes:
                if self._check_rdzv_completed():
                    self._group_nodes()
                    self._check_round += 1
            if self._rdzv_nodes:
                for group_idx, group in enumerate(self._node_groups):
                    if node_rank in group:
                        return self._rdzv_round, group_idx, dict(group)
                return self._rdzv_round, 0, {}
            return self._rdzv_round, 0, {}

    def _group_nodes(self):
        """Round r=1: adjacent pairs.  Round r>=2: reverse order so a
        suspect node lands with a different peer (reference's shuffle)."""
        ranks = list(self._rdzv_nodes.keys())
        if self._check_round % 2 == 1:
            ranks = ranks[::-1]
        groups = []
        for i in range(0, len(ranks), self.GROUP_SIZE):
            chunk = ranks[i : i + self.GROUP_SIZE]
            groups.append({r: self._rdzv_nodes[r] for r in chunk})
        # a trailing singleton can't run a pair check; merge it
        if len(groups) > 1 and len(groups[-1]) == 1:
            groups[-2].update(groups.pop())
        self._node_groups = groups
        logger.info(
            "network-check round %s groups: %s",
            self._check_round,
            [list(g.keys()) for g in groups],
        )

    def report_network_status(self, node_rank: int, succeeded: bool,
                              elapsed: float):
        with self._lock:
            prev = self._node_status.get(node_rank)
            # a success in any round clears earlier suspicion
            self._node_status[node_rank] = succeeded or bool(prev)
            if succeeded:
                self._node_times[node_rank] = elapsed
            # journaled without a version bump: health verdicts are
            # poll-read (check_fault_node), not delta-shipped
            self._journal_locked()

    def check_fault_node(self) -> Tuple[List[int], str]:
        with self._lock:
            if not self._rdzv_nodes:
                return [], NetworkFailureReason.NO_INIT
            expected = set(self._rdzv_nodes.keys())
            reported = set(self._node_status.keys())
            if not expected.issubset(reported):
                return [], NetworkFailureReason.WAITING_NODE
            faults = sorted(
                r for r in expected if not self._node_status[r]
            )
            reason = (
                NetworkFailureReason.NODE_FAILURE if faults else ""
            )
            return faults, reason

    def check_straggler(self) -> Tuple[List[int], str]:
        with self._lock:
            times = {
                r: t
                for r, t in self._node_times.items()
                if r in self._rdzv_nodes
            }
            if len(times) < 2:
                return [], ""
            values = sorted(times.values())
            median = values[len(values) // 2]
            if median <= 0:
                return [], ""
            stragglers = sorted(
                r
                for r, t in times.items()
                if t > self._straggler_ratio * median
                and not math.isclose(t, median)
            )
            return stragglers, ""

    def reset(self):
        with self._lock:
            self._node_status = {}
            self._node_times = {}
            self._check_round = 0

    def _export_extra_locked(self) -> dict:
        return {
            "node_status": {
                str(r): bool(v)
                for r, v in self._node_status.items()
            },
            "node_times": {
                str(r): float(v)
                for r, v in self._node_times.items()
            },
            "check_round": self._check_round,
            "node_groups": [
                {str(r): int(v) for r, v in g.items()}
                for g in self._node_groups
            ],
        }

    def _restore_extra_locked(self, state: dict):
        self._node_status = {
            int(k): bool(v)
            for k, v in (state.get("node_status") or {}).items()
        }
        self._node_times = {
            int(k): float(v)
            for k, v in (state.get("node_times") or {}).items()
        }
        self._check_round = int(state.get("check_round", 0))
        self._node_groups = [
            {int(k): int(v) for k, v in g.items()}
            for g in (state.get("node_groups") or [])
        ]
