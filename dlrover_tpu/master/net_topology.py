"""Topology-aware rank assignment.

Reference parity: ``dlrover/python/master/elastic_training/
net_topology.py:20,29,50`` — ``NodeTopologyMeta``, ``TopologyQuerier``
and ``DpTopologySorter`` (sort node ranks so nodes under the same
access/pod switch get adjacent ranks, keeping allreduce ring traffic
inside a switch).

TPU form: the hierarchy is slice / pod / superpod instead of
asw / psw; DCN-attached slices benefit the same way — data-parallel
neighbors inside one slice ride ICI, cross-slice hops ride DCN, so
adjacent ranks must cluster by (superpod, pod, slice).  The querier is
pluggable: on GCE the levels come from TPU-VM metadata
(``agent_hostname``/topology env), in tests from a static table.
"""

from abc import ABCMeta, abstractmethod
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass
class NodeTopologyMeta:
    """One node's position in the interconnect hierarchy (ref
    ``NodeTopologyMeta`` ``net_topology.py:20``)."""

    node_rank: int = 0
    process_num: int = 8
    # hierarchy labels, outermost first (superpod, pod, slice) — the
    # reference's (psw, asw) generalized to N levels
    levels: Tuple[str, ...] = ()


class TopologyQuerier(metaclass=ABCMeta):
    """Where a node sits (ref ``TopologyQuerier:29``)."""

    @abstractmethod
    def query(self, node_id: str) -> Optional[Tuple[str, ...]]:
        ...


class StaticTopologyQuerier(TopologyQuerier):
    """Table-driven querier (tests / config-file deployments)."""

    def __init__(self, table: Dict[str, Tuple[str, ...]]):
        self._table = dict(table)

    def query(self, node_id: str) -> Optional[Tuple[str, ...]]:
        return self._table.get(node_id)


def order_by_topology(ranks, levels_map: Dict[int, Tuple[str, ...]]):
    """Order node ranks so interconnect neighbors are adjacent: known
    nodes grouped by hierarchy labels (outermost first), unknown nodes
    appended in numeric order (missing metadata never blocks)."""
    known = [r for r in ranks if levels_map.get(r)]
    unknown = [r for r in ranks if not levels_map.get(r)]
    known.sort(key=lambda r: (levels_map[r], r))
    return known + unknown


class DpTopologySorter:
    """Sort nodes so interconnect neighbors get adjacent ranks (ref
    ``DpTopologySorter:50``); thin object facade over
    :func:`order_by_topology` (one ordering logic path)."""

    def sort(
        self, nodes: Dict[int, NodeTopologyMeta]
    ) -> Dict[int, NodeTopologyMeta]:
        """node_rank -> meta, returns the same metas re-ranked."""
        order = order_by_topology(
            sorted(nodes), {r: m.levels for r, m in nodes.items()}
        )
        out: Dict[int, NodeTopologyMeta] = {}
        for new_rank, orig_rank in enumerate(order):
            meta = nodes[orig_rank]
            meta.node_rank = new_rank
            out[new_rank] = meta
        return out
