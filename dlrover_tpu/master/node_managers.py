"""Per-node-type managers: chief / worker / evaluator accounting.

Reference parity: ``dlrover/python/master/node/training_node.py:154``
(``TrainingNodeManager`` base), ``node/worker.py:32,66,102``
(``ChiefManager`` / ``EvaluatorManager`` / ``WorkerManager``).  The PS
manager is out of TPU scope (SURVEY.md §2.8); chief and evaluator
carry over: a chief failure is job-fatal (it owns coordination state),
evaluators complete independently of the training workers, workers
carry the relaunch budget.
"""

from typing import Dict, List, Optional

from dlrover_tpu.common.constants import (
    NodeStatus,
    NodeType,
)
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.node import Node


class TrainingNodeManager:
    """Accounting for one node type (ref ``training_node.py:154``)."""

    node_type = NodeType.WORKER
    # a failure of this group kills the job (chief semantics)
    critical = False

    def __init__(self, max_relaunch_count: Optional[int] = None):
        """``max_relaunch_count`` overrides the per-node budget when
        given; None (default) honors each Node's own configured
        ``max_relaunch_count`` — a registry-level default would
        silently diverge from the env-configured budget."""
        self._nodes: Dict[int, Node] = {}
        self._max_relaunch = max_relaunch_count

    def add_node(self, node: Node):
        self._nodes[node.id] = node

    @property
    def nodes(self) -> Dict[int, Node]:
        return self._nodes

    def running_nodes(self) -> List[Node]:
        return [
            n
            for n in self._nodes.values()
            if n.status == NodeStatus.RUNNING
        ]

    def pending_nodes(self) -> List[Node]:
        return [
            n
            for n in self._nodes.values()
            if n.status in (NodeStatus.INITIAL, NodeStatus.PENDING)
        ]

    def all_finished(self) -> bool:
        return bool(self._nodes) and all(
            n.status in NodeStatus.end_states()
            for n in self._nodes.values()
        )

    def relaunchable(self, node: Node) -> bool:
        """May this node be relaunched after a failure? Delegates to
        the node's OWN budget (ref ``Node`` relaunch bookkeeping)
        unless the registry pins an override."""
        if not node.relaunchable:
            return False
        if self._max_relaunch is not None:
            return node.relaunch_count < self._max_relaunch
        return not node.exceeded_max_relaunch()

    def failure_is_fatal(self, node: Node) -> bool:
        """Does this failure end the job?"""
        return self.critical and not self.relaunchable(node)


class WorkerManager(TrainingNodeManager):
    """The allreduce training group (ref ``WorkerManager:102``)."""

    node_type = NodeType.WORKER
    critical = False


class ChiefManager(TrainingNodeManager):
    """The coordination-owning node (ref ``ChiefManager:32``): its
    unrecoverable failure is job-fatal."""

    node_type = NodeType.CHIEF
    critical = True


class EvaluatorManager(TrainingNodeManager):
    """Side evaluation nodes (ref ``EvaluatorManager:66``): they
    complete independently — the job may finish training while
    evaluation still runs, and eval failures never kill training."""

    node_type = NodeType.EVALUATOR
    critical = False

    def wait_for_evaluation(self) -> bool:
        """True when the job should keep running only for evaluators
        (training done, eval still in flight)."""
        return bool(self.running_nodes() or self.pending_nodes())


_MANAGER_TYPES = {
    NodeType.WORKER: WorkerManager,
    NodeType.CHIEF: ChiefManager,
    NodeType.EVALUATOR: EvaluatorManager,
}


class NodeGroupRegistry:
    """Routes nodes to their per-type manager (the reference keeps one
    manager per replica group inside DistributedJobManager)."""

    def __init__(self, max_relaunch_count: Optional[int] = None):
        self._managers: Dict[str, TrainingNodeManager] = {}
        self._max_relaunch = max_relaunch_count

    def manager(self, node_type: str) -> TrainingNodeManager:
        mgr = self._managers.get(node_type)
        if mgr is None:
            cls = _MANAGER_TYPES.get(node_type, TrainingNodeManager)
            mgr = cls(max_relaunch_count=self._max_relaunch)
            self._managers[node_type] = mgr
        return mgr

    def route(self, node: Node) -> TrainingNodeManager:
        mgr = self.manager(node.type)
        mgr.add_node(node)
        return mgr

    def training_finished(self) -> bool:
        """Training is done when chief+workers finished, regardless of
        evaluators (ref semantics: evaluation trails training)."""
        for node_type in (NodeType.CHIEF, NodeType.WORKER):
            mgr = self._managers.get(node_type)
            if mgr and mgr.nodes and not mgr.all_finished():
                return False
        return True

    def job_should_stop(self, failed_node: Node) -> bool:
        """A failure is job-fatal when its group says so."""
        mgr = self.manager(failed_node.type)
        fatal = mgr.failure_is_fatal(failed_node)
        if fatal:
            logger.error(
                "fatal failure: %s node %s exhausted its relaunch "
                "budget", failed_node.type, failed_node.id,
            )
        return fatal
