"""Failure diagnosis: data store + pluggable inference chain.

Reference parity: ``dlrover/python/master/diagnosis/`` —
``DiagnosisManager`` (``diagnosis.py:31``: collect ``DiagnosisData``,
periodic ``_diagnose_failures``), ``Diagnostician`` and the
``InferenceChain`` rule engine (``inferencechain/inference_chain.py:28``
with pluggable ``InferenceOperator``s).

TPU operators: step-stagnation (hang), OOM pattern in training logs,
chip unhealthy (libtpu error strings), preemption notice.
"""

import re
import threading
import time
from abc import ABCMeta, abstractmethod
from dataclasses import dataclass, field
from typing import Dict, List, Optional


class DiagnosisDataType:
    TRAINING_LOG = "training_log"
    CHIP_METRICS = "chip_metrics"
    AGENT_REPORT = "agent_report"


@dataclass
class DiagnosisData:
    data_type: str
    content: str
    node_rank: int = -1
    timestamp: float = field(default_factory=time.time)


@dataclass
class Inference:
    """A (problem, cause, action) conclusion."""

    problem: str
    cause: str = ""
    action: str = ""  # restart_process | relaunch_node | abort | none
    node_rank: int = -1


class InferenceOperator(metaclass=ABCMeta):
    @abstractmethod
    def infer(self, store: "DiagnosisDataStore") -> List[Inference]:
        ...


class DiagnosisDataStore:
    def __init__(self, window_secs: float = 1800.0):
        self._data: Dict[str, List[DiagnosisData]] = {}
        self._window = window_secs
        self._lock = threading.Lock()

    def add(self, data: DiagnosisData):
        with self._lock:
            bucket = self._data.setdefault(data.data_type, [])
            bucket.append(data)
            horizon = time.time() - self._window
            while bucket and bucket[0].timestamp < horizon:
                bucket.pop(0)

    def get(self, data_type: str) -> List[DiagnosisData]:
        with self._lock:
            return list(self._data.get(data_type, []))


class OomOperator(InferenceOperator):
    _PATTERN = re.compile(
        r"out of memory|oom-kill|RESOURCE_EXHAUSTED", re.IGNORECASE
    )

    def infer(self, store: DiagnosisDataStore) -> List[Inference]:
        results = []
        for d in store.get(DiagnosisDataType.TRAINING_LOG):
            if self._PATTERN.search(d.content):
                results.append(
                    Inference(
                        problem="oom",
                        cause="host or HBM memory exhausted",
                        action="relaunch_node",
                        node_rank=d.node_rank,
                    )
                )
        return results


class ChipErrorOperator(InferenceOperator):
    """libtpu / XLA hardware error signatures → node replacement."""

    _PATTERN = re.compile(
        r"(tpu.*(unhealthy|halted)|DEADLINE_EXCEEDED.*collective|"
        r"slice health|device or resource busy|uncorrectable)",
        re.IGNORECASE,
    )

    def infer(self, store: DiagnosisDataStore) -> List[Inference]:
        results = []
        for d in store.get(DiagnosisDataType.TRAINING_LOG):
            if self._PATTERN.search(d.content):
                results.append(
                    Inference(
                        problem="chip_error",
                        cause="TPU hardware/runtime fault",
                        action="relaunch_node",
                        node_rank=d.node_rank,
                    )
                )
        return results


class PreemptionOperator(InferenceOperator):
    _PATTERN = re.compile(
        r"(maintenance event|preempt|TERMINATING)", re.IGNORECASE
    )

    def infer(self, store: DiagnosisDataStore) -> List[Inference]:
        results = []
        for d in store.get(DiagnosisDataType.AGENT_REPORT):
            if self._PATTERN.search(d.content):
                results.append(
                    Inference(
                        problem="preemption",
                        cause="TPU-VM maintenance/spot reclaim",
                        action="relaunch_node",
                        node_rank=d.node_rank,
                    )
                )
        return results


class HangOperator(InferenceOperator):
    """Step stagnation from the SpeedMonitor."""

    def __init__(self, speed_monitor, hang_secs: Optional[float] = None):
        self._speed_monitor = speed_monitor
        self._hang_secs = hang_secs

    def infer(self, store: DiagnosisDataStore) -> List[Inference]:
        if self._speed_monitor and self._speed_monitor.step_is_stagnant(
            self._hang_secs
        ):
            return [
                Inference(
                    problem="hang",
                    cause="global step stagnant beyond threshold",
                    action="restart_process",
                )
            ]
        return []


class InferenceChain:
    def __init__(self, operators: List[InferenceOperator]):
        self._operators = operators

    def infer(self, store: DiagnosisDataStore) -> List[Inference]:
        conclusions = []
        for op in self._operators:
            conclusions.extend(op.infer(store))
        return conclusions


class DiagnosisManager:
    def __init__(
        self,
        speed_monitor=None,
        operators: Optional[List[InferenceOperator]] = None,
        interval: float = 60.0,
        conclusion_cooldown: float = 600.0,
    ):
        self.store = DiagnosisDataStore()
        self._cooldown = conclusion_cooldown
        self._emitted: Dict = {}
        if operators is None:
            operators = [
                OomOperator(),
                ChipErrorOperator(),
                PreemptionOperator(),
            ]
            if speed_monitor is not None:
                operators.append(HangOperator(speed_monitor))
        self.chain = InferenceChain(operators)
        self._interval = interval
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._conclusions: List[Inference] = []
        self._lock = threading.Lock()

    def collect_data(self, data: DiagnosisData):
        self.store.add(data)

    def diagnose(self) -> List[Inference]:
        """Run the chain, de-duplicating conclusions: the same
        (problem, node, action) fires at most once per cooldown — a
        single stored log line must not re-trigger restarts every
        cycle while it ages out of the data window."""
        conclusions = self.chain.infer(self.store)
        now = time.time()
        fresh = []
        with self._lock:
            for c in conclusions:
                key = (c.problem, c.node_rank, c.action)
                last = self._emitted.get(key, 0.0)
                if now - last < self._cooldown:
                    continue
                self._emitted[key] = now
                fresh.append(c)
            self._conclusions.extend(fresh)
        return fresh

    def take_conclusions(self) -> List[Inference]:
        """Consume pending conclusions (applied exactly once)."""
        with self._lock:
            out, self._conclusions = self._conclusions, []
            return out

    def start(self):
        if self._thread is not None:
            return

        def _loop():
            while not self._stopped.wait(self._interval):
                self.diagnose()

        self._thread = threading.Thread(
            target=_loop, name="diagnosis", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stopped.set()
