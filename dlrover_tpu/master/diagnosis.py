"""Failure diagnosis: data store + pluggable inference chain.

Reference parity: ``dlrover/python/master/diagnosis/`` —
``DiagnosisManager`` (``diagnosis.py:31``: collect ``DiagnosisData``,
periodic ``_diagnose_failures``), ``Diagnostician`` and the
``InferenceChain`` rule engine (``inferencechain/inference_chain.py:28``
with pluggable ``InferenceOperator``s).

TPU operators: step-stagnation (hang), OOM pattern in training logs,
chip unhealthy (libtpu error strings), preemption notice.
"""

import json
import re
import threading
import time
from abc import ABCMeta, abstractmethod
from dataclasses import dataclass, field
from typing import Dict, List, Optional


class DiagnosisDataType:
    TRAINING_LOG = "training_log"
    CHIP_METRICS = "chip_metrics"
    AGENT_REPORT = "agent_report"


@dataclass
class DiagnosisData:
    data_type: str
    content: str
    node_rank: int = -1
    timestamp: float = field(default_factory=time.time)


@dataclass
class Inference:
    """A (problem, cause, action) conclusion."""

    problem: str
    cause: str = ""
    action: str = ""  # restart_process | relaunch_node | abort | none
    node_rank: int = -1


class InferenceOperator(metaclass=ABCMeta):
    @abstractmethod
    def infer(self, store: "DiagnosisDataStore") -> List[Inference]:
        ...


class DiagnosisDataStore:
    def __init__(self, window_secs: float = 1800.0):
        self._data: Dict[str, List[DiagnosisData]] = {}
        self._window = window_secs
        self._lock = threading.Lock()

    def add(self, data: DiagnosisData):
        with self._lock:
            bucket = self._data.setdefault(data.data_type, [])
            bucket.append(data)
            horizon = time.time() - self._window
            while bucket and bucket[0].timestamp < horizon:
                bucket.pop(0)

    def get(self, data_type: str) -> List[DiagnosisData]:
        with self._lock:
            return list(self._data.get(data_type, []))


class OomOperator(InferenceOperator):
    _PATTERN = re.compile(
        r"out of memory|oom-kill|RESOURCE_EXHAUSTED", re.IGNORECASE
    )

    def infer(self, store: DiagnosisDataStore) -> List[Inference]:
        results = []
        for d in store.get(DiagnosisDataType.TRAINING_LOG):
            if self._PATTERN.search(d.content):
                results.append(
                    Inference(
                        problem="oom",
                        cause="host or HBM memory exhausted",
                        action="relaunch_node",
                        node_rank=d.node_rank,
                    )
                )
        return results


class ChipErrorOperator(InferenceOperator):
    """libtpu / XLA hardware error signatures → node replacement."""

    _PATTERN = re.compile(
        r"(tpu.*(unhealthy|halted)|DEADLINE_EXCEEDED.*collective|"
        r"slice health|device or resource busy|uncorrectable)",
        re.IGNORECASE,
    )

    def infer(self, store: DiagnosisDataStore) -> List[Inference]:
        results = []
        for d in store.get(DiagnosisDataType.TRAINING_LOG):
            if self._PATTERN.search(d.content):
                results.append(
                    Inference(
                        problem="chip_error",
                        cause="TPU hardware/runtime fault",
                        action="relaunch_node",
                        node_rank=d.node_rank,
                    )
                )
        return results


class PreemptionOperator(InferenceOperator):
    _PATTERN = re.compile(
        r"(maintenance event|preempt|TERMINATING)", re.IGNORECASE
    )

    def infer(self, store: DiagnosisDataStore) -> List[Inference]:
        results = []
        for d in store.get(DiagnosisDataType.AGENT_REPORT):
            if self._PATTERN.search(d.content):
                results.append(
                    Inference(
                        problem="preemption",
                        cause="TPU-VM maintenance/spot reclaim",
                        action="relaunch_node",
                        node_rank=d.node_rank,
                    )
                )
        return results


class HangOperator(InferenceOperator):
    """Step stagnation from the SpeedMonitor."""

    def __init__(self, speed_monitor, hang_secs: Optional[float] = None):
        self._speed_monitor = speed_monitor
        self._hang_secs = hang_secs

    def infer(self, store: DiagnosisDataStore) -> List[Inference]:
        if self._speed_monitor and self._speed_monitor.step_is_stagnant(
            self._hang_secs
        ):
            return [
                Inference(
                    problem="hang",
                    cause="global step stagnant beyond threshold",
                    action="restart_process",
                )
            ]
        return []


class GemmRegressionOperator(InferenceOperator):
    """Op-time regression over the resident profiler's GEMM census.

    The reference's xpu_timer watches per-kernel time for the whole
    job and flags slow kernels (``atorch/dev/xpu_timer/common/
    manager.h:201``).  Here the Trainer's ``trace_interval`` captures
    drop per-GEMM-cluster step times as CHIP_METRICS JSON (content
    carries a ``gemm_clusters`` list); this operator compares each
    cluster's newest per-step time against the median of its history
    and concludes when one slowed past ``ratio`` — the signature of a
    thermally throttled / degraded chip, which per-STEP timing alone
    cannot localize to an op."""

    def __init__(self, ratio: float = 1.5, min_history: int = 3):
        self._ratio = ratio
        self._min_history = min_history

    @staticmethod
    def _reports(store: DiagnosisDataStore, rank: int):
        out = []
        for d in store.get(DiagnosisDataType.CHIP_METRICS):
            if d.node_rank != rank:
                continue
            try:
                content = json.loads(d.content)
            except (TypeError, ValueError):
                continue
            if isinstance(content, dict) and content.get(
                "gemm_clusters"
            ):
                out.append(content)
        return out

    def infer(self, store: DiagnosisDataStore) -> List[Inference]:
        ranks = {
            d.node_rank
            for d in store.get(DiagnosisDataType.CHIP_METRICS)
        }
        results: List[Inference] = []
        for rank in ranks:
            reports = self._reports(store, rank)
            if len(reports) < self._min_history:
                continue
            # per-cluster per-step time series, oldest -> newest
            series: Dict[str, List[float]] = {}
            for rep in reports:
                steps = max(float(rep.get("steps", 1) or 1), 1.0)
                for row in rep["gemm_clusters"]:
                    key = row.get("key")
                    t = row.get("time_us")
                    if key is None or not t:
                        continue
                    series.setdefault(key, []).append(
                        float(t) / steps
                    )
            for key, ts in series.items():
                if len(ts) < self._min_history:
                    continue
                history = sorted(ts[:-1])
                baseline = history[len(history) // 2]  # median
                if baseline > 0 and ts[-1] > self._ratio * baseline:
                    results.append(
                        Inference(
                            problem="op_time_regression",
                            cause=(
                                f"GEMM cluster {key} per-step time "
                                f"{ts[-1]:.0f}us vs baseline "
                                f"{baseline:.0f}us "
                                f"(x{ts[-1] / baseline:.2f})"
                            ),
                            action="none",
                            node_rank=rank,
                        )
                    )
        return results


class InferenceChain:
    def __init__(self, operators: List[InferenceOperator]):
        self._operators = operators

    def infer(self, store: DiagnosisDataStore) -> List[Inference]:
        conclusions = []
        for op in self._operators:
            conclusions.extend(op.infer(store))
        return conclusions


class DiagnosisManager:
    def __init__(
        self,
        speed_monitor=None,
        operators: Optional[List[InferenceOperator]] = None,
        interval: float = 60.0,
        conclusion_cooldown: float = 600.0,
    ):
        self.store = DiagnosisDataStore()
        self._cooldown = conclusion_cooldown
        self._emitted: Dict = {}
        if operators is None:
            operators = [
                OomOperator(),
                ChipErrorOperator(),
                PreemptionOperator(),
                GemmRegressionOperator(),
            ]
            if speed_monitor is not None:
                operators.append(HangOperator(speed_monitor))
        self.chain = InferenceChain(operators)
        self._interval = interval
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._conclusions: List[Inference] = []
        self._lock = threading.Lock()

    def collect_data(self, data: DiagnosisData):
        self.store.add(data)

    def diagnose(self) -> List[Inference]:
        """Run the chain, de-duplicating conclusions: the same
        (problem, node, action) fires at most once per cooldown — a
        single stored log line must not re-trigger restarts every
        cycle while it ages out of the data window."""
        conclusions = self.chain.infer(self.store)
        now = time.time()
        fresh = []
        with self._lock:
            for c in conclusions:
                key = (c.problem, c.node_rank, c.action)
                last = self._emitted.get(key, 0.0)
                if now - last < self._cooldown:
                    continue
                self._emitted[key] = now
                fresh.append(c)
            self._conclusions.extend(fresh)
        return fresh

    def take_conclusions(self) -> List[Inference]:
        """Consume pending conclusions (applied exactly once)."""
        with self._lock:
            out, self._conclusions = self._conclusions, []
            return out

    def start(self):
        if self._thread is not None:
            return

        def _loop():
            while not self._stopped.wait(self._interval):
                self.diagnose()

        self._thread = threading.Thread(
            target=_loop, name="diagnosis", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stopped.set()
