"""Failure diagnosis: data store + pluggable inference chain.

Reference parity: ``dlrover/python/master/diagnosis/`` —
``DiagnosisManager`` (``diagnosis.py:31``: collect ``DiagnosisData``,
periodic ``_diagnose_failures``), ``Diagnostician`` and the
``InferenceChain`` rule engine (``inferencechain/inference_chain.py:28``
with pluggable ``InferenceOperator``s).

TPU operators: step-stagnation (hang), OOM pattern in training logs,
chip unhealthy (libtpu error strings), preemption notice.
"""

import json
import os
import re
import threading
import time
from abc import ABCMeta, abstractmethod
from collections import deque
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

from dlrover_tpu.common.log import default_logger as logger


class DiagnosisDataType:
    TRAINING_LOG = "training_log"
    CHIP_METRICS = "chip_metrics"
    AGENT_REPORT = "agent_report"


@dataclass
class DiagnosisData:
    data_type: str
    content: str
    node_rank: int = -1
    timestamp: float = field(default_factory=time.time)


@dataclass
class Inference:
    """A (problem, cause, action) conclusion."""

    problem: str
    cause: str = ""
    action: str = ""  # restart_process | relaunch_node | abort | none
    node_rank: int = -1


class InferenceOperator(metaclass=ABCMeta):
    @abstractmethod
    def infer(self, store: "DiagnosisDataStore") -> List[Inference]:
        ...


class DiagnosisDataStore:
    """Windowed diagnosis evidence, bucketed by data type.

    Buckets are ``deque``s bounded BOTH ways: by age (``window_secs``,
    evicted on every add) and by length (``max_per_type`` via the
    deque's own ``maxlen``) — high-rate CHIP_METRICS used to pay an
    O(n) ``list.pop(0)`` per eviction AND could grow without bound
    inside the window."""

    def __init__(
        self, window_secs: float = 1800.0, max_per_type: int = 2048
    ):
        self._data: Dict[str, "deque[DiagnosisData]"] = {}
        self._window = window_secs
        self._max_per_type = max(int(max_per_type), 1)
        self._lock = threading.Lock()

    def add(self, data: DiagnosisData):
        with self._lock:
            bucket = self._data.get(data.data_type)
            if bucket is None:
                bucket = self._data[data.data_type] = deque(
                    maxlen=self._max_per_type
                )
            bucket.append(data)
            horizon = time.time() - self._window
            while bucket and bucket[0].timestamp < horizon:
                bucket.popleft()

    def get(self, data_type: str) -> List[DiagnosisData]:
        with self._lock:
            return list(self._data.get(data_type, ()))


class OomOperator(InferenceOperator):
    _PATTERN = re.compile(
        r"out of memory|oom-kill|RESOURCE_EXHAUSTED", re.IGNORECASE
    )

    def infer(self, store: DiagnosisDataStore) -> List[Inference]:
        results = []
        for d in store.get(DiagnosisDataType.TRAINING_LOG):
            if self._PATTERN.search(d.content):
                results.append(
                    Inference(
                        problem="oom",
                        cause="host or HBM memory exhausted",
                        action="relaunch_node",
                        node_rank=d.node_rank,
                    )
                )
        return results


class ChipErrorOperator(InferenceOperator):
    """libtpu / XLA hardware error signatures → node replacement."""

    _PATTERN = re.compile(
        r"(tpu.*(unhealthy|halted)|DEADLINE_EXCEEDED.*collective|"
        r"slice health|device or resource busy|uncorrectable)",
        re.IGNORECASE,
    )

    def infer(self, store: DiagnosisDataStore) -> List[Inference]:
        results = []
        for d in store.get(DiagnosisDataType.TRAINING_LOG):
            if self._PATTERN.search(d.content):
                results.append(
                    Inference(
                        problem="chip_error",
                        cause="TPU hardware/runtime fault",
                        action="relaunch_node",
                        node_rank=d.node_rank,
                    )
                )
        return results


class PreemptionOperator(InferenceOperator):
    _PATTERN = re.compile(
        r"(maintenance event|preempt|TERMINATING)", re.IGNORECASE
    )

    def infer(self, store: DiagnosisDataStore) -> List[Inference]:
        results = []
        for d in store.get(DiagnosisDataType.AGENT_REPORT):
            if self._PATTERN.search(d.content):
                results.append(
                    Inference(
                        problem="preemption",
                        cause="TPU-VM maintenance/spot reclaim",
                        action="relaunch_node",
                        node_rank=d.node_rank,
                    )
                )
        return results


class HangOperator(InferenceOperator):
    """Step stagnation from the SpeedMonitor."""

    def __init__(self, speed_monitor, hang_secs: Optional[float] = None):
        self._speed_monitor = speed_monitor
        self._hang_secs = hang_secs

    def infer(self, store: DiagnosisDataStore) -> List[Inference]:
        if self._speed_monitor and self._speed_monitor.step_is_stagnant(
            self._hang_secs
        ):
            return [
                Inference(
                    problem="hang",
                    cause="global step stagnant beyond threshold",
                    action="restart_process",
                )
            ]
        return []


class GemmRegressionOperator(InferenceOperator):
    """Op-time regression over the resident profiler's GEMM census.

    The reference's xpu_timer watches per-kernel time for the whole
    job and flags slow kernels (``atorch/dev/xpu_timer/common/
    manager.h:201``).  Here the Trainer's ``trace_interval`` captures
    drop per-GEMM-cluster step times as CHIP_METRICS JSON (content
    carries a ``gemm_clusters`` list); this operator compares each
    cluster's newest per-step time against the median of its history
    and concludes when one slowed past ``ratio`` — the signature of a
    thermally throttled / degraded chip, which per-STEP timing alone
    cannot localize to an op."""

    def __init__(self, ratio: float = 1.5, min_history: int = 3):
        self._ratio = ratio
        self._min_history = min_history

    @staticmethod
    def _reports(store: DiagnosisDataStore, rank: int):
        out = []
        for d in store.get(DiagnosisDataType.CHIP_METRICS):
            if d.node_rank != rank:
                continue
            try:
                content = json.loads(d.content)
            except (TypeError, ValueError):
                continue
            if isinstance(content, dict) and content.get(
                "gemm_clusters"
            ):
                out.append(content)
        return out

    def infer(self, store: DiagnosisDataStore) -> List[Inference]:
        ranks = {
            d.node_rank
            for d in store.get(DiagnosisDataType.CHIP_METRICS)
        }
        results: List[Inference] = []
        for rank in ranks:
            reports = self._reports(store, rank)
            if len(reports) < self._min_history:
                continue
            # per-cluster per-step time series, oldest -> newest
            series: Dict[str, List[float]] = {}
            for rep in reports:
                steps = max(float(rep.get("steps", 1) or 1), 1.0)
                for row in rep["gemm_clusters"]:
                    key = row.get("key")
                    t = row.get("time_us")
                    if key is None or not t:
                        continue
                    series.setdefault(key, []).append(
                        float(t) / steps
                    )
            for key, ts in series.items():
                if len(ts) < self._min_history:
                    continue
                history = sorted(ts[:-1])
                baseline = history[len(history) // 2]  # median
                if baseline > 0 and ts[-1] > self._ratio * baseline:
                    results.append(
                        Inference(
                            problem="op_time_regression",
                            cause=(
                                f"GEMM cluster {key} per-step time "
                                f"{ts[-1]:.0f}us vs baseline "
                                f"{baseline:.0f}us "
                                f"(x{ts[-1] / baseline:.2f})"
                            ),
                            action="none",
                            node_rank=rank,
                        )
                    )
        return results


def _attribution_hint(health_engine, node: int) -> str:
    """"; dominant device time: copy 40%" when the live attribution
    profiler has a step_profile-derived share for the node, "" when
    not (profiler off, old engine, or test facade without the
    accessor) — conclusions cite WHY, not just WHO."""
    accessor = getattr(health_engine, "attribution", None)
    if not callable(accessor):
        return ""
    try:
        dominant = accessor().get(node)
    except Exception:  # noqa: BLE001 - advisory context only
        return ""
    if not dominant:
        return ""
    category, share = dominant
    return f"; dominant device time: {category} {share:.0%}"


class StragglerOperator(InferenceOperator):
    """Relative straggler verdicts from the observatory's streaming
    step-time EWMAs (``observability/health.py``): a node whose EWMA
    exceeds the across-node median by the engine's ratio is concluded
    a straggler.  Replaces nothing — per-STEP timing at the master was
    simply never derived before; the network-check manager only sees
    the pre-flight rounds.  With the live attribution profiler on,
    the cause cites the node's dominant device-time category (a
    straggler at 40% copy share is an offload problem, not a bad
    host)."""

    def __init__(self, health_engine):
        self._health = health_engine

    def infer(self, store: "DiagnosisDataStore") -> List[Inference]:
        del store  # derived from the timeline, not the evidence store
        return [
            Inference(
                problem="straggler",
                cause=(
                    f"step time x{score:.2f} vs across-node median "
                    f"(ratio {self._health.straggler_ratio:.2f})"
                    + _attribution_hint(self._health, node)
                ),
                action="none",
                node_rank=node,
            )
            for node, score in self._health.stragglers()
        ]


class DataStallOperator(InferenceOperator):
    """Chronic input starvation from the goodput ledger's
    ``data_stall`` spans: when a node's windowed stall share (by
    stage) passes ``share_threshold``, conclude the stage that
    stalls.  The ledger already proved the share is pure loss —
    this operator just names the node and the stage."""

    def __init__(self, health_engine, share_threshold: float = 0.3):
        self._health = health_engine
        self._threshold = share_threshold

    def infer(self, store: "DiagnosisDataStore") -> List[Inference]:
        del store
        results = []
        for node, shares in self._health.stall_shares().items():
            stage, share = max(
                shares.items(), key=lambda kv: kv[1]
            )
            if share < self._threshold:
                continue
            results.append(
                Inference(
                    problem="data_stall",
                    cause=(
                        f"{stage} stall share {share:.0%} of the "
                        f"window (threshold "
                        f"{self._threshold:.0%})"
                        + _attribution_hint(self._health, node)
                    ),
                    action="none",
                    node_rank=node,
                )
            )
        return results


class HangWatchdogOperator(InferenceOperator):
    """Per-node hang via the observatory's span-heartbeat watchdog:
    a node whose agent still heartbeats but whose processes emitted
    no timeline event for the watchdog window is concluded hung.
    Unlike :class:`HangOperator` this needs no ``GlobalStep``
    reports, and it NAMES the wedged node — the global step keeps
    advancing while one rank hangs in a collective, which is exactly
    the case the SpeedMonitor cannot see."""

    def __init__(self, health_engine):
        self._health = health_engine

    def infer(self, store: "DiagnosisDataStore") -> List[Inference]:
        del store
        return [
            Inference(
                problem="hang",
                cause=(
                    f"no timeline event for {silence:.0f}s "
                    f"(watchdog {self._health.hang_watchdog_s:.0f}s)"
                    " while the node is otherwise alive"
                ),
                action="restart_process",
                node_rank=node,
            )
            for node, silence in self._health.hang_suspects()
        ]


class MasterOverloadOperator(InferenceOperator):
    """The control plane diagnosing ITSELF: each diagnose cycle is
    one derivation interval of the ``MasterHealth`` deriver
    (``observability/health.py``) — sustained p99 RPC latency,
    write-behind queue-near-bound, journal-lag and pool-saturation
    streaks become ``master_overload`` conclusions.  ``action`` is
    ``none`` on purpose: the remedy (raise
    ``DLROVER_TPU_MASTER_WORKERS``, shard the job off this master) is
    an operator decision, not a node relaunch — but the conclusion
    rides the same timeline/status/Brain surfaces as every fleet
    verdict, so the signal chain covers its own substrate."""

    def __init__(self, master_health):
        self._master_health = master_health

    def infer(self, store: "DiagnosisDataStore") -> List[Inference]:
        del store  # derived from self-telemetry, not the evidence
        # the reason rides the PROBLEM key ("master_overload:<reason>")
        # on purpose: the manager dedupes on (problem, node, action),
        # and a journal_lag breach must not be swallowed for 600 s
        # because a pool_saturated verdict fired first — MasterHealth
        # keeps reasons independent, the conclusion keys must too
        return [
            Inference(
                problem=f"master_overload:{v['reason']}",
                cause=(
                    f"{v['reason']} at {v['value']:g} vs threshold "
                    f"{v['threshold']:g} for {v['streak']} intervals"
                ),
                action="none",
                node_rank=-1,
            )
            for v in self._master_health.evaluate()
        ]


class InferenceChain:
    def __init__(self, operators: List[InferenceOperator]):
        self._operators = operators

    def infer(self, store: DiagnosisDataStore) -> List[Inference]:
        conclusions = []
        for op in self._operators:
            conclusions.extend(op.infer(store))
        return conclusions


#: cadence of the background diagnose loop (env-overridable so the
#: chaos scenario and tests can run many intervals in seconds)
DIAGNOSIS_INTERVAL_ENV = "DLROVER_TPU_DIAGNOSIS_INTERVAL_S"


class DiagnosisManager:
    #: conclusion problems that auto-trigger ONE throttled deep
    #: capture of the named rank (the CaptureCoordinator's per-node
    #: cooldown owns the throttle) — the xpu_timer reflex: a hang or
    #: sustained straggler verdict is exactly when you want stacks +
    #: an op trace of that rank
    CAPTURE_PROBLEMS = frozenset({"hang", "straggler"})

    def __init__(
        self,
        speed_monitor=None,
        operators: Optional[List[InferenceOperator]] = None,
        interval: Optional[float] = None,
        conclusion_cooldown: float = 600.0,
        health_engine=None,
        datastore=None,
        job: str = "",
        capture=None,
        master_health=None,
    ):
        """With a ``health_engine`` (the observatory is on) the chain
        sits ON TOP of the streaming derivations: straggler /
        data-stall / per-node hang operators join the log-pattern
        operators, and the SpeedMonitor hang rule is subsumed by the
        span-heartbeat watchdog.  Conclusions are then recorded as
        ``diagnosis`` instants on the timeline and persisted to the
        Brain ``node_events`` table (``datastore``) so they survive
        master failover.  Without an engine the manager is exactly
        the pre-observatory one."""
        self.store = DiagnosisDataStore()
        self._cooldown = conclusion_cooldown
        self._emitted: Dict = {}
        self._health = health_engine
        self._datastore = datastore
        #: CaptureCoordinator (master/capture.py) — None when the
        #: profiler is kill-switched; fresh hang/straggler
        #: conclusions then trigger nothing extra, exactly as today
        self._capture = capture
        self._job = job or os.getenv("DLROVER_TPU_JOB_NAME", "default")
        if operators is None:
            operators = [
                OomOperator(),
                ChipErrorOperator(),
                PreemptionOperator(),
                GemmRegressionOperator(),
            ]
            if health_engine is not None:
                operators.extend(
                    [
                        StragglerOperator(health_engine),
                        DataStallOperator(health_engine),
                        HangWatchdogOperator(health_engine),
                    ]
                )
            if master_health is not None:
                # the diagnose loop's cadence IS the MasterHealth
                # derivation interval — the master's own overload
                # verdicts join the chain like any fleet signal
                operators.append(
                    MasterOverloadOperator(master_health)
                )
            if speed_monitor is not None:
                # the whole-job stagnation rule stays EVEN WITH the
                # watchdog: the two see different failure shapes (the
                # watchdog names a silent node; this one catches a
                # job whose every node idles inside open spans), and
                # their conclusion keys differ so the cooldown dedupe
                # keeps them from stacking restarts
                operators.append(HangOperator(speed_monitor))
        self.chain = InferenceChain(operators)
        if interval is None:
            from dlrover_tpu.common.env import env_float

            interval = env_float(DIAGNOSIS_INTERVAL_ENV, 60.0)
        self._interval = interval
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._conclusions: List[Inference] = []
        #: newest conclusions kept for the status snapshot (NOT
        #: consumed by take_conclusions, which feeds the node manager)
        self._recent: "deque[dict]" = deque(maxlen=64)
        self._lock = threading.Lock()

    def collect_data(self, data: DiagnosisData):
        self.store.add(data)

    def _record_conclusion(self, c: Inference, now: float):
        """One fresh conclusion onto the timeline (``diagnosis``
        instant) and into the Brain sqlite — the observatory's audit
        trail survives master failover.  Best-effort: recording must
        never block or break the diagnose loop."""
        if self._health is None:
            return  # observatory off: today's (unrecorded) behavior
        from dlrover_tpu.observability.events import get_event_logger

        try:
            get_event_logger().instant(
                "diagnosis",
                problem=c.problem,
                action=c.action,
                node_rank=c.node_rank,
                cause=c.cause,
            )
        except Exception as e:  # noqa: BLE001
            logger.warning("diagnosis instant emit failed: %s", e)
        if self._datastore is not None:
            try:
                self._datastore.record_node_event(
                    self._job,
                    str(c.node_rank),
                    "diagnosis",
                    json.dumps(
                        {**asdict(c), "t": now},
                        separators=(",", ":"),
                    ),
                )
            except Exception as e:  # noqa: BLE001
                logger.warning("diagnosis persist failed: %s", e)
        if (
            self._capture is not None
            and c.node_rank >= 0
            and c.problem in self.CAPTURE_PROBLEMS
        ):
            # deep-capture reflex: ask the named rank for stacks +
            # an N-step trace.  The coordinator's per-node cooldown
            # and in-flight dedupe make this at most ONE capture per
            # window no matter how many conclusions repeat.
            try:
                self._capture.request(c.node_rank, reason=c.problem)
            except Exception as e:  # noqa: BLE001
                logger.warning("capture trigger failed: %s", e)

    def diagnose(self) -> List[Inference]:
        """Run the chain, de-duplicating conclusions: the same
        (problem, node, action) fires at most once per cooldown — a
        single stored log line must not re-trigger restarts every
        cycle while it ages out of the data window."""
        conclusions = self.chain.infer(self.store)
        now = time.time()
        fresh = []
        with self._lock:
            for c in conclusions:
                key = (c.problem, c.node_rank, c.action)
                last = self._emitted.get(key, 0.0)
                if now - last < self._cooldown:
                    continue
                self._emitted[key] = now
                fresh.append(c)
                self._recent.append({**asdict(c), "t": now})
            self._conclusions.extend(fresh)
        for c in fresh:
            self._record_conclusion(c, now)
        return fresh

    def recent_conclusions(self, limit: int = 16) -> List[dict]:
        """Newest de-duplicated conclusions (not consumed — the
        status snapshot's view; ``take_conclusions`` still owns the
        apply-exactly-once contract)."""
        with self._lock:
            out = list(self._recent)
        return out[-limit:] if limit else out

    def take_conclusions(self) -> List[Inference]:
        """Consume pending conclusions (applied exactly once)."""
        with self._lock:
            out, self._conclusions = self._conclusions, []
            return out

    def start(self):
        if self._thread is not None:
            return

        def _loop():
            while not self._stopped.wait(self._interval):
                self.diagnose()

        self._thread = threading.Thread(
            target=_loop, name="diagnosis", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stopped.set()
