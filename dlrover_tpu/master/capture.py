"""The deep-capture arm's master side: who gets captured, when, and
what came back.

A diagnosis conclusion (hang watchdog, sustained straggler) or an
operator request asks :meth:`CaptureCoordinator.request` for a deep
capture of one rank.  The coordinator:

- **throttles** per node (``DLROVER_TPU_CAPTURE_COOLDOWN_S`` + an
  in-flight dedupe): repeated conclusions about the same wedged rank
  produce ONE capture per window, not a storm of profiler signals at
  a struggling node;
- **delivers** by posting a ``capture`` directive on a
  :class:`~dlrover_tpu.master.brain.NodeDirectives` slot — the PR-10
  piggyback: the directive rides the target agent's next
  monitor-pacing ``WaitingNodeNum`` poll, zero extra RPCs;
- **collects** the agent's ``ProfileReport`` (parsed summary + the
  artifact path holding stacks and the trace profile), keeps the
  newest per node for ``/status`` / ``top.py``'s "why" surface, and
  persists a row to the Brain ``profiles`` table so the evidence
  survives master failover;
- **journals** its state as the ``capture`` component of the PR-7
  ``ControlPlaneJournal``: a failed-over master re-arms an in-flight
  capture directive under the SAME id (the directive died with the
  old master's memory) and keeps the cooldown anchors, so a capture
  neither vanishes nor double-fires across a failover.

Constructed only when the observatory AND ``DLROVER_TPU_PROFILE`` are
on — kill-switched off, no directives ride the wire and ``/status``
carries no ``profiles`` key, exactly today's surface.
"""

import threading
import time
from typing import Callable, Dict, Optional

from dlrover_tpu.common.env import capture_cooldown_s
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.master.brain import NodeDirectives

#: the directive verb the agent understands (next to brain's "drain")
DIRECTIVE_CAPTURE = "capture"


class CaptureCoordinator:
    """Master-side owner of the deep-capture lifecycle."""

    def __init__(
        self,
        job: str = "",
        datastore=None,
        cooldown_s: Optional[float] = None,
        directives: Optional[NodeDirectives] = None,
    ):
        self._job = job or "default"
        self._datastore = datastore
        self._cooldown = (
            capture_cooldown_s() if cooldown_s is None else cooldown_s
        )
        self.directives = directives or NodeDirectives()
        self._lock = threading.Lock()
        #: node -> wall time of the last REQUESTED capture (the
        #: cooldown anchor; requesting consumes the window even if
        #: the node never answers — a wedged rank must not be
        #: re-signalled every diagnosis sweep)
        self._last_request: Dict[int, float] = {}
        #: node -> {"id", "reason", "t"} awaiting a ProfileReport
        self._in_flight: Dict[int, dict] = {}
        #: node -> newest completed capture entry (the /status view)
        self._latest: Dict[int, dict] = {}
        self._next_id = 1
        self._journal_cb: Optional[Callable[[str, dict], None]] = None

    # ----------------------------------------------------------- request
    def request(self, node: int, reason: str = "") -> Optional[int]:
        """Ask ``node`` for a deep capture; returns the capture id or
        None when throttled (cooldown / already in flight)."""
        node = int(node)
        now = time.time()
        with self._lock:
            pending = self._in_flight.get(node)
            if pending is not None:
                # a stale in-flight entry (agent died before
                # reporting) expires with the cooldown so the node
                # stays capturable
                if now - pending["t"] < self._cooldown:
                    return None
                self._in_flight.pop(node, None)
            if now - self._last_request.get(node, 0.0) < self._cooldown:
                return None
            capture_id = self._next_id
            self._next_id += 1
            self._last_request[node] = now
            self._in_flight[node] = {
                "id": capture_id,
                "reason": reason,
                "t": now,
            }
        self.directives.post(
            node, DIRECTIVE_CAPTURE, reason, capture_id
        )
        logger.info(
            "capture %d requested of node %s (%s)",
            capture_id, node, reason or "operator",
        )
        self._journal()
        return capture_id

    # ------------------------------------------------------------ result
    def record_result(
        self,
        node: int,
        summary: Optional[dict] = None,
        artifact: str = "",
        reason: str = "",
        capture_id: int = 0,
    ):
        """One agent's ``ProfileReport`` landed: expose it and make
        it durable."""
        node = int(node)
        now = time.time()
        with self._lock:
            pending = self._in_flight.pop(node, None)
            if pending is not None:
                reason = reason or pending["reason"]
                capture_id = capture_id or pending["id"]
            entry = {
                "node": node,
                "id": capture_id,
                "reason": reason,
                "t": now,
                "summary": summary or {},
                "artifact": artifact,
            }
            self._latest[node] = entry
        if self._datastore is not None:
            try:
                self._datastore.record_profile(
                    self._job,
                    node,
                    kind="capture",
                    reason=reason,
                    summary=summary or {},
                    artifact=artifact,
                )
            except Exception as e:  # noqa: BLE001 - durability is best-effort
                logger.warning("capture persist failed: %s", e)
        logger.info(
            "capture %d of node %s landed (%s)",
            capture_id, node, artifact or "no artifact",
        )
        self._journal()

    def latest(self) -> Dict[int, dict]:
        """Newest capture per node — the ``/status``/``top.py``
        surface (in-flight requests show with ``summary=None`` so the
        operator can see a capture is underway)."""
        with self._lock:
            out = {n: dict(e) for n, e in self._latest.items()}
            for node, pending in self._in_flight.items():
                if node not in out or out[node]["id"] < pending["id"]:
                    out[node] = {
                        "node": node,
                        "id": pending["id"],
                        "reason": pending["reason"],
                        "t": pending["t"],
                        "summary": None,
                        "artifact": "",
                    }
            return out

    # ------------------------------------------------- journal contract
    def set_journal(self, cb: Optional[Callable[[str, dict], None]]):
        self._journal_cb = cb

    def _journal(self):
        if self._journal_cb is None:
            return
        try:
            self._journal_cb("state", self.export_state())
        except Exception as e:  # noqa: BLE001
            logger.warning("capture journal failed: %s", e)

    def export_state(self) -> dict:
        with self._lock:
            return {
                "next_id": self._next_id,
                "last_request": {
                    str(n): t for n, t in self._last_request.items()
                },
                "in_flight": {
                    str(n): dict(e)
                    for n, e in self._in_flight.items()
                },
                "latest": {
                    str(n): dict(e) for n, e in self._latest.items()
                },
            }

    def restore_state(self, state: dict):
        """Journal replay: cooldown anchors and results come back,
        and an in-flight capture re-arms its directive under the SAME
        id — it died with the old incarnation's memory, like a PR-10
        drain."""
        with self._lock:
            self._next_id = max(
                int(state.get("next_id", 1)), self._next_id
            )
            self._last_request = {
                int(n): float(t)
                for n, t in (state.get("last_request") or {}).items()
            }
            self._in_flight = {
                int(n): dict(e)
                for n, e in (state.get("in_flight") or {}).items()
            }
            self._latest = {
                int(n): dict(e)
                for n, e in (state.get("latest") or {}).items()
            }
            pending = list(self._in_flight.items())
        for node, entry in pending:
            self.directives.post(
                node,
                DIRECTIVE_CAPTURE,
                entry.get("reason", ""),
                int(entry.get("id", 0)),
            )
            logger.info(
                "capture %s of node %s re-armed after failover",
                entry.get("id"), node,
            )
