"""ElasticJob / ScalePlan controller — the operator's reconcile loops.

Reference parity: the Go kubebuilder operator
(``dlrover/go/operator/pkg/controllers/elasticjob_controller.go:85,182``
— reconcile ElasticJob by creating the job master pod;
``scaleplan_controller.go:79,95`` — apply a ScalePlan's replica specs /
create / remove / migrate pods).  Behavior parity in Python: a
poll-and-reconcile loop over the CRDs (same shapes as ``k8s/crds/``),
driving pods through the same ``k8sClient`` surface the scalers use —
so the whole control plane runs without any Go build.

The client is duck-typed (``scheduler.kubernetes.k8sClient`` in
production, a fake in tests), needing:
``list_pods/create_pod/delete_pod`` and
``list_custom_resource/update_custom_resource_status``.
"""

import threading
import time
from typing import Dict, List, Optional

from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.common.log import default_logger as logger

GROUP = "elastic.dlrover-tpu.io"
VERSION = "v1alpha1"
ELASTICJOB_PLURAL = "elasticjobs"
SCALEPLAN_PLURAL = "scaleplans"

MASTER_SUFFIX = "-dlrover-master"


def update_condition(
    status: Dict,
    cond_type: str,
    cond_status: bool,
    reason: str = "",
    message: str = "",
) -> Dict:
    """Maintain a k8s-style conditions list on a CRD status
    (reference ``dlrover/go/operator/pkg/common/condition.go`` —
    ``setCondition``/``updateJobConditions``): one entry per type,
    ``lastTransitionTime`` touched only when the boolean status
    actually flips."""
    now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    want = "True" if cond_status else "False"
    conditions = list(status.get("conditions") or [])
    for cond in conditions:
        if cond.get("type") == cond_type:
            if cond.get("status") != want:
                cond["lastTransitionTime"] = now
            cond.update(
                status=want, reason=reason, message=message
            )
            break
    else:
        conditions.append(
            {
                "type": cond_type,
                "status": want,
                "reason": reason,
                "message": message,
                "lastTransitionTime": now,
            }
        )
    status["conditions"] = conditions
    return status


def _pod_resource(node_spec: Dict) -> Optional[Dict]:
    """Resource hints out of an optimizer node spec ({"type", "memory"
    (MB), "cpu", ...}) — non-resource keys dropped."""
    if not isinstance(node_spec, dict):
        return None
    resource = {
        k: v for k, v in node_spec.items() if k in ("memory", "cpu")
    }
    return resource or None


def master_pod_manifest(job: Dict) -> Dict:
    """Master pod for an ElasticJob (ref ``pkg/controllers/master/
    master.go`` — image/env from the job spec, master command)."""
    name = job["metadata"]["name"]
    spec = job.get("spec", {})
    replica_specs = spec.get("replicaSpecs", {})
    worker = replica_specs.get(NodeType.WORKER, {})
    template = worker.get("template", {}) or {}
    image = "python:3.12"
    containers = (
        template.get("spec", {}).get("containers") or [{}]
    )
    if containers and containers[0].get("image"):
        image = containers[0]["image"]
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": f"{name}{MASTER_SUFFIX}",
            "labels": {
                "job": name,
                "node-type": "master",
                "app": "dlrover-tpu",
            },
            "ownerReferences": [
                {
                    "apiVersion": f"{GROUP}/{VERSION}",
                    "kind": "ElasticJob",
                    "name": name,
                    "uid": job["metadata"].get("uid", ""),
                }
            ],
        },
        "spec": {
            "restartPolicy": "Never",
            "containers": [
                {
                    "name": "master",
                    "image": image,
                    "command": [
                        "python", "-m", "dlrover_tpu.master.main",
                        "--platform", "k8s",
                        "--job_name", name,
                    ],
                }
            ],
        },
    }


def worker_pod_manifest(
    job_name: str,
    node_id: int,
    resource: Optional[Dict] = None,
    template: Optional[Dict] = None,
) -> Dict:
    """Worker pod from the ElasticJob's worker template (image /
    command / env carried over, like ``TpuPodScaler._pod_manifest``),
    plus the rank contract env vars agents expect."""
    tmpl_spec = (template or {}).get("spec", {}) or {}
    containers = tmpl_spec.get("containers") or [{}]
    base = dict(containers[0]) if containers else {}
    container = {
        "name": base.get("name", "worker"),
        "image": base.get("image", "python:3.12"),
    }
    for key in ("command", "args", "env", "resources"):
        if base.get(key):
            container[key] = base[key]
    env = list(container.get("env", []))
    env += [
        {"name": "DLROVER_TPU_JOB_NAME", "value": job_name},
        {"name": "NODE_RANK", "value": str(node_id)},
    ]
    container["env"] = env
    if resource:
        # optimizer resource hints: numbers are MB of host memory.
        # Merge INTO the template's resources — replacing the block
        # would drop limits like google.com/tpu and schedule a
        # replacement worker with no chips
        resources = dict(container.get("resources", {}))
        requests = dict(resources.get("requests", {}))
        if "memory" in resource:
            requests["memory"] = f"{int(resource['memory'])}Mi"
        if "cpu" in resource:
            requests["cpu"] = str(resource["cpu"])
        resources["requests"] = requests
        container["resources"] = resources
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": f"{job_name}-worker-{node_id}",
            "labels": {
                "job": job_name,
                "node-type": NodeType.WORKER,
                "node-id": str(node_id),
                "app": "dlrover-tpu",
            },
        },
        "spec": {
            "restartPolicy": "Never",
            "containers": [container],
        },
    }


class ElasticJobController:
    """Poll-and-reconcile controller for both CRDs."""

    def __init__(self, client, resync_interval: float = 5.0):
        self._client = client
        self._interval = resync_interval
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # plans already applied (or attempted) by THIS controller,
        # keyed by (name, uid) -> [outcome phase, patched?]: a failed
        # status patch must retry only the patch (then stop — endless
        # re-patching would churn the CR with watch events every
        # resync), and a mid-apply failure must not re-execute creates
        # with fresh worker ids
        self._applied_plans: Dict[tuple, list] = {}

    # -- ElasticJob ------------------------------------------------------
    def reconcile_elasticjob(self, job: Dict):
        """Ensure the job's master pod exists (the master then owns
        worker lifecycle through its scaler — exactly the reference
        split: operator creates the master, master creates workers)."""
        name = job["metadata"]["name"]
        phase = (job.get("status") or {}).get("phase", "")
        if phase in ("Succeeded", "Failed"):
            return
        master_name = f"{name}{MASTER_SUFFIX}"
        pods = self._pods_by_name(f"job={name}")
        if master_name not in pods:
            logger.info("reconcile ElasticJob %s: creating master", name)
            self._client.create_pod(master_pod_manifest(job))
            status = dict(job.get("status") or {})
            status["phase"] = "Running"
            update_condition(
                status, "MasterCreated", True,
                reason="MasterPodCreated",
                message=f"master pod {master_name} created",
            )
            update_condition(
                status, "Running", True, reason="JobRunning",
                message="job master is supervising the job",
            )
            self._set_status(ELASTICJOB_PLURAL, name, status)

    # -- ScalePlan -------------------------------------------------------
    def reconcile_scaleplan(self, plan: Dict):
        """Apply a ScalePlan: replica targets, explicit creates,
        removals and migrations (ref ``scaleplan_controller.go:95``).

        Field dialect matches what the in-repo producers emit:
        ``ElasticJobScaler`` writes the optimizer's
        ``node_group_resources`` verbatim, so replica targets are
        accepted as ``replicas`` OR ``count``; ``launch_nodes`` entries
        carry ``{"type", "memory"(MB), ...}``; ``migratePods`` values
        are node specs (``{"type": ...}``), not k8s resources."""
        name = plan["metadata"]["name"]
        # key by (name, uid): a deleted-and-recreated plan with a
        # reused name is a NEW plan, not an applied one
        plan_key = (name, plan["metadata"].get("uid", ""))
        status = plan.get("status") or {}
        if status.get("phase") in ("Succeeded", "Failed"):
            return
        if plan_key in self._applied_plans:
            entry = self._applied_plans[plan_key]
            if not entry[1]:  # applied but the status patch failed
                entry[1] = self._set_status(
                    SCALEPLAN_PLURAL, name,
                    self._plan_status(entry[0], status),
                )
            return
        spec = plan.get("spec", {})
        owner = spec.get("ownerJob", "")
        # reads first: a transient list failure here must stay
        # retryable (nothing has been mutated yet)
        template = self._worker_template(owner)
        # at-most-once from HERE: mark before the first mutation — a
        # mid-apply failure must not re-execute creates with fresh
        # worker ids every resync (unbounded pod growth); a partially-
        # applied plan is surfaced as Failed instead of silently
        # retried
        self._applied_plans[plan_key] = ["Failed", False]

        # replica targets: diff current worker pods against the target
        replica_specs = spec.get("replicaResourceSpecs", {}) or {}
        worker_target = replica_specs.get(NodeType.WORKER, {})
        target = worker_target.get(
            "replicas", worker_target.get("count")
        )
        if target is not None:
            self._scale_workers(
                owner, int(target), worker_target.get("resource"),
                template,
            )

        for pod in spec.get("createPods", []) or []:
            if "id" in pod:
                node_id = int(pod["id"])
            else:
                node_id = self._next_worker_id(owner)
            self._client.create_pod(
                worker_pod_manifest(
                    owner, node_id, _pod_resource(pod), template
                )
            )
        for pod_name in spec.get("removePods", []) or []:
            self._delete_quietly(pod_name)
        for old_name, node_spec in (spec.get("migratePods") or {}).items():
            # create the replacement first, then drain the old pod
            self._client.create_pod(
                worker_pod_manifest(
                    owner,
                    self._next_worker_id(owner),
                    _pod_resource(node_spec),
                    template,
                )
            )
            self._delete_quietly(old_name)
        patched = self._set_status(
            SCALEPLAN_PLURAL, name,
            self._plan_status("Succeeded", status),
        )
        self._applied_plans[plan_key] = ["Succeeded", patched]

    @staticmethod
    def _plan_status(phase: str, existing: Optional[Dict] = None) -> Dict:
        """ScalePlan status with a condition trail (ref
        ``scaleplan_types.go:29-126`` phase + conditions).  Starts
        from the CR's EXISTING status so ``lastTransitionTime`` only
        moves when the condition actually flips."""
        status: Dict = dict(existing or {})
        status["phase"] = phase
        update_condition(
            status, "Applied", phase == "Succeeded",
            reason=(
                "PlanApplied"
                if phase == "Succeeded"
                else "PlanApplyFailed"
            ),
            message=(
                "all creates/removes/migrations executed"
                if phase == "Succeeded"
                else "plan application did not complete; pods may be "
                "partially scaled"
            ),
        )
        return status

    def _worker_template(self, job_name: str) -> Optional[Dict]:
        """The owner ElasticJob's worker pod template (workers must run
        the job's image/command, not a placeholder)."""
        if not job_name:
            return None
        for job in self._list(ELASTICJOB_PLURAL):
            if job["metadata"]["name"] == job_name:
                return (
                    job.get("spec", {})
                    .get("replicaSpecs", {})
                    .get(NodeType.WORKER, {})
                    .get("template")
                )
        return None

    def _scale_workers(self, job_name: str, target: int,
                       resource: Optional[Dict],
                       template: Optional[Dict] = None):
        workers = self._worker_pods(job_name)
        current = len(workers)
        if current < target:
            existing = {
                int(p["metadata"]["labels"].get("node-id", -1))
                for p in workers.values()
            }
            nid = 0
            for _ in range(target - current):
                while nid in existing:
                    nid += 1
                existing.add(nid)
                self._client.create_pod(
                    worker_pod_manifest(
                        job_name, nid, resource, template
                    )
                )
        elif current > target:
            # remove the highest node-ids first (stable rank prefix)
            doomed = sorted(
                workers.values(),
                key=lambda p: int(
                    p["metadata"]["labels"].get("node-id", 0)
                ),
                reverse=True,
            )[: current - target]
            for pod in doomed:
                self._delete_quietly(pod["metadata"]["name"])

    # -- loop ------------------------------------------------------------
    def reconcile_once(self):
        for job in self._list(ELASTICJOB_PLURAL):
            try:
                self.reconcile_elasticjob(job)
            except Exception as e:  # noqa: BLE001
                logger.warning("ElasticJob reconcile failed: %s", e)
        plans = self._list(SCALEPLAN_PLURAL)
        live = {
            (p["metadata"]["name"], p["metadata"].get("uid", ""))
            for p in plans
        }
        # prune bookkeeping for deleted plans (a recreated name+uid is
        # a fresh plan and must be applied)
        self._applied_plans = {
            k: v for k, v in self._applied_plans.items() if k in live
        }
        for plan in plans:
            try:
                self.reconcile_scaleplan(plan)
            except Exception as e:  # noqa: BLE001
                logger.warning("ScalePlan reconcile failed: %s", e)

    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="elasticjob-controller", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stopped.set()

    def _loop(self):
        while not self._stopped.wait(self._interval):
            try:
                self.reconcile_once()
            except Exception as e:  # noqa: BLE001
                logger.warning("reconcile cycle failed: %s", e)

    # -- client helpers --------------------------------------------------
    def _list(self, plural: str) -> List[Dict]:
        try:
            out = self._client.list_custom_resource(
                GROUP, VERSION, plural
            )
        except Exception:  # noqa: BLE001
            return []
        return list(out.get("items", []))

    def _set_status(
        self, plural: str, name: str, status: Dict
    ) -> bool:
        try:
            self._client.update_custom_resource_status(
                GROUP, VERSION, plural, name, {"status": status}
            )
            return True
        except Exception as e:  # noqa: BLE001
            logger.warning("status update failed for %s: %s", name, e)
            return False

    def _pods_by_name(self, selector: str) -> Dict[str, Dict]:
        pods = self._client.list_pods(selector)
        if isinstance(pods, dict):
            items = pods.get("items", [])
        else:  # kubernetes client object (V1PodList)
            items = pods.items
        out = {}
        for p in items:
            d = p if isinstance(p, dict) else p.to_dict()
            out[d["metadata"]["name"]] = d
        return out

    def _worker_pods(self, job_name: str) -> Dict[str, Dict]:
        return self._pods_by_name(
            f"job={job_name},node-type={NodeType.WORKER}"
        )

    def _next_worker_id(self, job_name: str) -> int:
        ids = [
            int(p["metadata"]["labels"].get("node-id", -1))
            for p in self._worker_pods(job_name).values()
        ]
        return (max(ids) + 1) if ids else 0

    def _delete_quietly(self, pod_name: str):
        try:
            self._client.delete_pod(pod_name)
        except Exception as e:  # noqa: BLE001
            logger.warning("delete %s failed: %s", pod_name, e)
