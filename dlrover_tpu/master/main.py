"""Master CLI: ``python -m dlrover_tpu.master.main --port ... --node-num N``.

Reference parity: ``dlrover/python/master/main.py:43-70`` +
``master/args.py``.
"""

import argparse
import sys

from dlrover_tpu.common.log import default_logger as logger


def parse_master_args(argv=None):
    parser = argparse.ArgumentParser(description="dlrover_tpu job master")
    parser.add_argument("--port", type=int, default=0,
                        help="gRPC port (0 = pick a free port)")
    parser.add_argument("--node_num", "--node-num", dest="node_num",
                        type=int, default=1)
    parser.add_argument("--platform", default="local",
                        choices=["local", "k8s", "ray"])
    parser.add_argument("--job_name", default="local-job")
    parser.add_argument("--pending_timeout", type=int, default=900)
    parser.add_argument(
        "--brain_db", default="",
        help="sqlite path for the durable Brain datastore (speed "
        "history, strategy calibration, node events survive master "
        "restarts); also via $DLROVER_TPU_BRAIN_DB",
    )
    parser.add_argument(
        "--workers", type=int, default=0,
        help="gRPC thread-pool size (0 = $DLROVER_TPU_MASTER_WORKERS "
        "or 64).  Each parked long-poll holds one worker for its "
        "whole wait — raise this before a 256+ agent fan-in; the "
        "occupancy gauges say when.",
    )
    parser.add_argument(
        "--status_port", type=int, default=None,
        help="serve plain-HTTP /metrics (Prometheus text) + /status "
        "(observatory JSON snapshot) on this port (0 = pick a free "
        "one; omit = off).  Also via $DLROVER_TPU_STATUS_PORT.",
    )
    return parser.parse_args(argv)


def run(args) -> int:
    import os

    from dlrover_tpu.common.env import get_free_port
    from dlrover_tpu.master.master import (
        DistributedJobMaster,
        LocalJobMaster,
    )

    if args.brain_db:
        os.environ["DLROVER_TPU_BRAIN_DB"] = args.brain_db
    if args.workers:
        # through the env so the servicer's parked-wait cap, the
        # create_master_service pool and the occupancy gauge all read
        # ONE value
        os.environ["DLROVER_TPU_MASTER_WORKERS"] = str(args.workers)
    if args.status_port is not None:
        os.environ["DLROVER_TPU_STATUS_PORT"] = str(args.status_port)
    os.environ.setdefault("DLROVER_TPU_JOB_NAME", args.job_name)

    port = args.port or get_free_port()
    if args.platform == "local":
        master = LocalJobMaster(port, args.node_num)
    else:
        # platform-appropriate scaler: without one, BOTH the relaunch
        # path and the autoscale cycle are observers only
        if args.platform == "k8s":
            from dlrover_tpu.master.scaler import ElasticJobScaler

            scaler = ElasticJobScaler(args.job_name)
        else:
            # ray masters get their ActorScaler from the ray
            # scheduler layer (needs a live client); no default here
            scaler = None
        master = DistributedJobMaster(
            port,
            args.node_num,
            scaler=scaler,
            pending_timeout=args.pending_timeout,
        )
    master.prepare()
    logger.info("job %s master listening on %s", args.job_name,
                master.addr)
    print(f"DLROVER_TPU_MASTER_ADDR={master.addr}", flush=True)
    if master.status_server is not None:
        # the BOUND port (a requested 0 resolves here)
        print(
            f"DLROVER_TPU_STATUS_PORT={master.status_server.port}",
            flush=True,
        )
    return master.run()


def main(argv=None) -> int:
    return run(parse_master_args(argv))


if __name__ == "__main__":
    sys.exit(main())
