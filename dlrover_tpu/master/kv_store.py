"""Master KV-store service: the rendezvous/bootstrap store the agents
use to exchange small blobs (e.g. the jax coordinator address).

Reference parity: ``dlrover/python/master/elastic_training/
kv_store_service.py:18``.

``wait`` blocks on a ``threading.Condition`` notified by every mutation
(``set``/``add``/``delete``) — a waiter wakes the moment its key
appears instead of busy-polling; this is also the primitive the
control-plane long-poll ``get`` (``KVWaitRequest``) parks on, so an
idle remote waiter costs one RPC and zero master CPU.
"""

import threading
import time
from typing import Dict, Optional


class KVStoreService:
    def __init__(self):
        self._cond = threading.Condition()
        self._store: Dict[str, bytes] = {}

    def _mutated(self):
        """Caller holds the condition: wake every parked waiter."""
        self._cond.notify_all()

    def set(self, key: str, value: bytes):
        with self._cond:
            self._store[key] = value
            self._mutated()

    def get(self, key: str) -> bytes:
        with self._cond:
            return self._store.get(key, b"")

    def add(self, key: str, delta: int) -> int:
        """Atomic counter (torch-Store-style add semantics)."""
        with self._cond:
            current = int(self._store.get(key, b"0") or b"0")
            current += delta
            self._store[key] = str(current).encode()
            self._mutated()
            return current

    def wait(self, key: str, timeout: float = 30.0) -> Optional[bytes]:
        """Block until ``key`` holds a non-empty value; None on
        timeout.  Event-driven: sleeps on the condition, woken by the
        mutation that publishes the key."""
        deadline = time.monotonic() + max(timeout, 0.0)
        with self._cond:
            while True:
                value = self._store.get(key, b"")
                if value:
                    return value
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._cond.wait(remaining)

    def delete(self, key: str):
        with self._cond:
            self._store.pop(key, None)
            self._mutated()

    def clear(self):
        with self._cond:
            self._store.clear()
            self._mutated()
