"""Master KV-store service: the rendezvous/bootstrap store the agents
use to exchange small blobs (e.g. the jax coordinator address).

Reference parity: ``dlrover/python/master/elastic_training/
kv_store_service.py:18``.

``wait`` blocks on a ``threading.Condition`` notified by every mutation
(``set``/``add``/``delete``) — a waiter wakes the moment its key
appears instead of busy-polling; this is also the primitive the
control-plane long-poll ``get`` (``KVWaitRequest``) parks on, so an
idle remote waiter costs one RPC and zero master CPU.

Durability: every mutation can be journaled through an attached
callback (``set_journal``) so a restarted master replays identical KV
contents — ``add`` journals its RESULT (an idempotent ``set``), so a
replay that overlaps a snapshot can never double-count.
"""

import base64
import threading
import time
from typing import Callable, Dict, Optional


class KVStoreService:
    def __init__(self):
        self._cond = threading.Condition()
        self._store: Dict[str, bytes] = {}
        self._journal_cb: Optional[Callable[[str, dict], None]] = None

    def set_journal(self, cb: Optional[Callable[[str, dict], None]]):
        """``cb(op, args)`` invoked (under the lock, so journal order
        is mutation order) on every state change."""
        self._journal_cb = cb

    def _journal(self, op: str, **args):
        """Caller holds the condition."""
        if self._journal_cb is not None:
            self._journal_cb(op, args)

    def _mutated(self):
        """Caller holds the condition: wake every parked waiter."""
        self._cond.notify_all()

    def set(self, key: str, value: bytes):
        with self._cond:
            self._store[key] = value
            self._journal(
                "set",
                key=key,
                value_b64=base64.b64encode(value).decode(),
            )
            self._mutated()

    def get(self, key: str) -> bytes:
        with self._cond:
            return self._store.get(key, b"")

    def add(self, key: str, delta: int) -> int:
        """Atomic counter (torch-Store-style add semantics)."""
        with self._cond:
            current = int(self._store.get(key, b"0") or b"0")
            current += delta
            value = str(current).encode()
            self._store[key] = value
            # journal the RESULT, not the delta: replay is idempotent
            self._journal(
                "set",
                key=key,
                value_b64=base64.b64encode(value).decode(),
            )
            self._mutated()
            return current

    def wait(self, key: str, timeout: float = 30.0) -> Optional[bytes]:
        """Block until ``key`` holds a non-empty value; None on
        timeout.  Event-driven: sleeps on the condition, woken by the
        mutation that publishes the key."""
        deadline = time.monotonic() + max(timeout, 0.0)
        with self._cond:
            while True:
                value = self._store.get(key, b"")
                if value:
                    return value
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._cond.wait(remaining)

    def delete(self, key: str):
        with self._cond:
            self._store.pop(key, None)
            self._journal("delete", key=key)
            self._mutated()

    def clear(self):
        with self._cond:
            self._store.clear()
            self._journal("clear")
            self._mutated()

    # --------------------------------------------- failover replay
    def export_state(self) -> dict:
        """JSON-safe full state for the compacted snapshot."""
        with self._cond:
            return {
                "kv": {
                    k: base64.b64encode(v).decode()
                    for k, v in self._store.items()
                }
            }

    def restore_state(self, state: dict):
        """Install a snapshot (replay path — NOT journaled: restoring
        a journaled state must not re-journal it)."""
        with self._cond:
            self._store = {
                k: base64.b64decode(v)
                for k, v in (state.get("kv") or {}).items()
            }
            self._mutated()

    def apply_journal_op(self, op: str, args: dict):
        """Re-apply one journaled mutation (replay path)."""
        with self._cond:
            if op == "set":
                self._store[args["key"]] = base64.b64decode(
                    args.get("value_b64", "")
                )
            elif op == "delete":
                self._store.pop(args.get("key", ""), None)
            elif op == "clear":
                self._store.clear()
            self._mutated()
