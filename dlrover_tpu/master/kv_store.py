"""Master KV-store service: the rendezvous/bootstrap store the agents
use to exchange small blobs (e.g. the jax coordinator address).

Reference parity: ``dlrover/python/master/elastic_training/
kv_store_service.py:18``.
"""

import threading
import time
from typing import Dict, Optional


class KVStoreService:
    def __init__(self):
        self._lock = threading.Lock()
        self._store: Dict[str, bytes] = {}

    def set(self, key: str, value: bytes):
        with self._lock:
            self._store[key] = value

    def get(self, key: str) -> bytes:
        with self._lock:
            return self._store.get(key, b"")

    def add(self, key: str, delta: int) -> int:
        """Atomic counter (torch-Store-style add semantics)."""
        with self._lock:
            current = int(self._store.get(key, b"0") or b"0")
            current += delta
            self._store[key] = str(current).encode()
            return current

    def wait(self, key: str, timeout: float = 30.0) -> Optional[bytes]:
        deadline = time.time() + timeout
        while time.time() < deadline:
            value = self.get(key)
            if value:
                return value
            time.sleep(0.05)
        return None

    def delete(self, key: str):
        with self._lock:
            self._store.pop(key, None)

    def clear(self):
        with self._lock:
            self._store.clear()
