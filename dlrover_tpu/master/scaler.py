"""Scalers: execute ScalePlans against the cluster substrate.

Reference parity: ``dlrover/python/master/scaler/`` — ``Scaler`` ABC
(``base_scaler.py``), ``PodScaler`` (``pod_scaler.py:77``: direct pod
create with a retry queue), ``ElasticJobScaler``
(``elasticjob_scaler.py``: writes a ScalePlan CRD for the operator).

TPU redesign: a "node" is a TPU-VM worker.  ``TpuPodScaler`` drives the
k8s API when the ``kubernetes`` package exists (TPU GKE pods/JobSet);
``InMemoryScaler`` is the test double (reference tests mock k8sClient
the same way, SURVEY.md §4).
"""

import threading
import time
from abc import ABCMeta, abstractmethod
from typing import Dict, List, Optional

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.messages import ScalePlan
from dlrover_tpu.common.node import Node, NodeResource
from dlrover_tpu.common.constants import NodeStatus, NodeType


class Scaler(metaclass=ABCMeta):
    def __init__(self, job_name: str = "job"):
        self._job_name = job_name

    @abstractmethod
    def scale(self, plan: ScalePlan):
        ...


class InMemoryScaler(Scaler):
    """Records plans and materializes fake nodes — the unit-test
    substrate (and the local single-host mode, where 'scaling' only
    bookkeeps)."""

    def __init__(self, job_name: str = "job"):
        super().__init__(job_name)
        self.plans: List[ScalePlan] = []
        self.alive: Dict[str, Node] = {}
        self._next_id = 0

    def scale(self, plan: ScalePlan):
        self.plans.append(plan)
        for node_type, group in plan.node_group_resources.items():
            count = group.get("count", 0)
            existing = [
                n for n in self.alive.values() if n.type == node_type
            ]
            for _ in range(max(0, count - len(existing))):
                node = Node(
                    node_type=node_type,
                    node_id=self._next_id,
                    status=NodeStatus.PENDING,
                )
                self.alive[node.name] = node
                self._next_id += 1
            # scale-down: drop the newest nodes beyond the target
            excess = len(existing) - count
            if excess > 0:
                for node in sorted(
                    existing, key=lambda n: n.id, reverse=True
                )[:excess]:
                    self.alive.pop(node.name, None)
                    node.update_status(NodeStatus.DELETED)
        for name in plan.remove_nodes:
            node = self.alive.pop(name, None)
            if node:
                node.update_status(NodeStatus.DELETED)
        for node_spec in plan.launch_nodes:
            self._launch(node_spec)
        # migrate = launch the replacement, then remove the old node
        # (the Brain's drain_replace plan; TpuPodScaler mirrors this)
        for name, node_spec in plan.migrate_nodes.items():
            self._launch(node_spec)
            node = self.alive.pop(name, None)
            if node:
                node.update_status(NodeStatus.DELETED)

    def _launch(self, node_spec: Dict):
        node = Node(
            node_type=node_spec.get("type", NodeType.WORKER),
            node_id=self._next_id,
            config_resource=NodeResource(
                cpu=node_spec.get("cpu", 0),
                memory=node_spec.get("memory", 0),
                tpu_chips=node_spec.get("tpu_chips", 0),
            ),
            status=NodeStatus.PENDING,
        )
        self.alive[node.name] = node
        self._next_id += 1


class TpuPodScaler(Scaler):
    """Creates/removes TPU worker pods through the k8s API with a retry
    queue (reference ``PodScaler`` ``pod_scaler.py:77,163,303``).

    The k8s client is injected so tests run without a cluster; when the
    ``kubernetes`` package is absent this scaler refuses to build
    (local mode uses ``InMemoryScaler``).
    """

    def __init__(
        self,
        job_name: str,
        namespace: str = "default",
        k8s_client=None,
        pod_template: Optional[Dict] = None,
        retry_interval: float = 5.0,
        max_retries: int = 3,
    ):
        super().__init__(job_name)
        if k8s_client is None:
            from dlrover_tpu.scheduler.kubernetes import k8sClient

            k8s_client = k8sClient.singleton_instance(namespace)
        self._client = k8s_client
        self._namespace = namespace
        self._pod_template = pod_template or {}
        self._retry_interval = retry_interval
        self._max_retries = max_retries
        self._retry_queue: List = []
        self._lock = threading.Lock()
        self._retry_thread: Optional[threading.Thread] = None

    def _pod_manifest(self, node_type: str, node_id: int,
                      resource: Dict) -> Dict:
        """TPU worker pod: the template carries the TPU nodeSelector
        (``cloud.google.com/gke-tpu-topology`` etc.); per-node env
        carries the rank contract."""
        import copy

        from dlrover_tpu.common.constants import NodeEnv

        manifest = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": f"{self._job_name}-{node_type}-{node_id}",
                "labels": {
                    "app": "dlrover-tpu",
                    "job": self._job_name,
                    "node-type": node_type,
                    "node-id": str(node_id),
                },
            },
            # deep copy: env appended below must not mutate the shared
            # template across pods
            "spec": copy.deepcopy(self._pod_template),
        }
        containers = manifest["spec"].setdefault(
            "containers",
            [{"name": "trainer", "image": resource.get("image", "")}],
        )
        env = containers[0].setdefault("env", [])
        env.extend(
            [
                {"name": NodeEnv.NODE_RANK, "value": str(node_id)},
                {"name": NodeEnv.JOB_NAME, "value": self._job_name},
            ]
        )
        return manifest

    def _existing_ids(self, node_type: str):
        """Live pod ids + names from labels (id reuse after a
        mid-range death would 409 on AlreadyExists)."""
        pods = self._client.list_pods(
            f"job={self._job_name},node-type={node_type}"
        )
        ids = {}
        for pod in pods.items:
            labels = pod.metadata.labels or {}
            try:
                ids[int(labels.get("node-id", "-1"))] = pod.metadata.name
            except ValueError:
                continue
        ids.pop(-1, None)
        return ids

    def scale(self, plan: ScalePlan):
        for node_type, group in plan.node_group_resources.items():
            count = group.get("count", 0)
            existing = self._existing_ids(node_type)
            missing = count - len(existing)
            if missing > 0:
                next_id = max(existing, default=-1) + 1
                for i in range(missing):
                    self._create_pod(node_type, next_id + i, group)
            elif missing < 0:
                # scale-down: remove the highest-id pods
                for node_id in sorted(existing, reverse=True)[:-missing]:
                    self._remove_pod(existing[node_id])
        for name in plan.remove_nodes:
            self._remove_pod(name)
        # launch_nodes: replacement pods with fresh ids and per-node
        # resource overrides (OOM memory growth etc.)
        for node_spec in plan.launch_nodes:
            node_type = node_spec.get("type", NodeType.WORKER)
            existing = self._existing_ids(node_type)
            self._create_pod(
                node_type, max(existing, default=-1) + 1, node_spec
            )
        # migrate = launch replacement, then remove the old pod
        for name, node_spec in plan.migrate_nodes.items():
            node_type = node_spec.get("type", NodeType.WORKER)
            existing = self._existing_ids(node_type)
            self._create_pod(
                node_type, max(existing, default=-1) + 1, node_spec
            )
            self._remove_pod(name)

    def _create_pod(self, node_type: str, node_id: int, resource: Dict,
                    attempt: int = 0):
        manifest = self._pod_manifest(node_type, node_id, resource)
        try:
            self._client.create_pod(manifest)
        except Exception as e:  # noqa: BLE001
            if attempt < self._max_retries:
                logger.warning(
                    "pod create failed (%s); queueing retry", e
                )
                with self._lock:
                    self._retry_queue.append(
                        (node_type, node_id, resource, attempt + 1)
                    )
                self._ensure_retry_thread()
            else:
                logger.error("pod create permanently failed: %s", e)

    def _remove_pod(self, name: str):
        try:
            self._client.delete_pod(name)
        except Exception as e:  # noqa: BLE001
            logger.warning("pod delete failed for %s: %s", name, e)

    def _ensure_retry_thread(self):
        if self._retry_thread is not None:
            return

        def _loop():
            while True:
                time.sleep(self._retry_interval)
                with self._lock:
                    queue, self._retry_queue = self._retry_queue, []
                    if not queue:
                        # exit decision under the lock: a concurrent
                        # enqueue either lands before this (we drain
                        # it next loop) or sees _retry_thread=None and
                        # spawns a fresh thread
                        self._retry_thread = None
                        return
                for node_type, node_id, resource, attempt in queue:
                    self._create_pod(
                        node_type, node_id, resource, attempt
                    )

        self._retry_thread = threading.Thread(
            target=_loop, name="pod-scaler-retry", daemon=True
        )
        self._retry_thread.start()


class ElasticJobScaler(Scaler):
    """Writes a ScalePlan custom resource for an external operator to
    reconcile (reference ``elasticjob_scaler.py``)."""

    def __init__(self, job_name: str, namespace: str = "default",
                 k8s_client=None):
        super().__init__(job_name)
        if k8s_client is None:
            from dlrover_tpu.scheduler.kubernetes import k8sClient

            k8s_client = k8sClient.singleton_instance(namespace)
        self._client = k8s_client
        self._namespace = namespace
        self._plan_index = 0

    def scale(self, plan: ScalePlan):
        body = {
            "apiVersion": "elastic.dlrover-tpu.io/v1alpha1",
            "kind": "ScalePlan",
            "metadata": {
                "name": f"{self._job_name}-scaleplan-{self._plan_index}",
                "labels": {"elasticjob-name": self._job_name},
            },
            "spec": {
                "ownerJob": self._job_name,
                "replicaResourceSpecs": plan.node_group_resources,
                "createPods": plan.launch_nodes,
                "removePods": plan.remove_nodes,
                "migratePods": plan.migrate_nodes,
            },
        }
        self._client.create_custom_resource(
            group="elastic.dlrover-tpu.io",
            version="v1alpha1",
            plural="scaleplans",
            body=body,
        )
        self._plan_index += 1
