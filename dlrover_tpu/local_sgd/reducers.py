"""Pseudo-gradient reducers for local SGD.

Reference parity: ``atorch/atorch/local_sgd/reduce_methods/`` —
``linear.py`` (plain mean) and ``generalized_task_arithmetic.py``
(``GTAReducer``: sign-consensus + magnitude-weighted merge, which
suppresses conflicting replica updates instead of averaging them
away).
"""

from typing import List

import jax
import jax.numpy as jnp


def linear_reduce(deltas: List):
    """Plain mean over per-replica delta pytrees."""
    n = len(deltas)
    return jax.tree_util.tree_map(
        lambda *xs: sum(xs) / n, *deltas
    )


def gta_reduce(
    deltas: List,
    consensus_threshold: float = 0.0,
):
    """Generalized task arithmetic: keep, per element, only replicas
    agreeing with the dominant sign (by summed magnitude), then
    magnitude-weighted average them."""

    def merge(*xs):
        stack = jnp.stack(xs).astype(jnp.float32)  # [R, ...]
        mag = jnp.abs(stack)
        pos = jnp.sum(jnp.where(stack > 0, mag, 0.0), axis=0)
        neg = jnp.sum(jnp.where(stack < 0, mag, 0.0), axis=0)
        dominant = jnp.where(pos >= neg, 1.0, -1.0)
        agree = jnp.sign(stack) == dominant
        # consensus mask: drop elements where agreement share is low
        share = jnp.mean(agree.astype(jnp.float32), axis=0)
        keep = share >= consensus_threshold
        w = jnp.where(agree, mag, 0.0)
        denom = jnp.sum(w, axis=0)
        merged = jnp.where(
            denom > 0,
            jnp.sum(w * stack, axis=0) / jnp.maximum(denom, 1e-12),
            jnp.mean(stack, axis=0),
        )
        return jnp.where(keep, merged, jnp.mean(stack, axis=0)).astype(
            xs[0].dtype
        )

    return jax.tree_util.tree_map(merge, *deltas)
