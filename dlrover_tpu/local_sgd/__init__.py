from dlrover_tpu.local_sgd.diloco import (  # noqa: F401
    DiLoCoState,
    diloco_init,
    diloco_outer_step,
)
from dlrover_tpu.local_sgd.reducers import (  # noqa: F401
    gta_reduce,
    linear_reduce,
)
