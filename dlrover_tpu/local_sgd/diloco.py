"""DiLoCo-style local SGD: infrequent cross-replica sync + outer opt.

Reference parity: ``atorch/atorch/local_sgd/`` — local-SGD on
FSDP/HSDP with an outer optimizer in the runtime
(``HSDP/_runtime_utils.py:143,268``).  Functional JAX form: replicas
run H inner steps independently (no per-step gradient sync — the DCN
win for multi-slice TPU), then the *pseudo-gradient* (anchor - params,
reduced across replicas) feeds an outer Nesterov-momentum optimizer.

Usage inside a jitted sync step over the mesh, or eagerly across
slices; the reduce is a ``pmean`` (or a robust reducer from
``reducers.py``).
"""

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax


class DiLoCoState(NamedTuple):
    anchor: optax.Params  # params at last sync
    outer_opt_state: optax.OptState
    sync_count: jnp.ndarray


def default_outer_optimizer(
    learning_rate: float = 0.7, momentum: float = 0.9
) -> optax.GradientTransformation:
    """DiLoCo's published outer optimizer: SGD w/ Nesterov momentum."""
    return optax.sgd(
        learning_rate, momentum=momentum, nesterov=True
    )


def diloco_init(params, outer_optimizer=None) -> DiLoCoState:
    outer_optimizer = outer_optimizer or default_outer_optimizer()
    return DiLoCoState(
        anchor=jax.tree_util.tree_map(jnp.copy, params),
        outer_opt_state=outer_optimizer.init(params),
        sync_count=jnp.zeros((), jnp.int32),
    )


def diloco_outer_step(
    params,
    state: DiLoCoState,
    outer_optimizer=None,
    axis_name: Optional[str] = None,
    reducer=None,
):
    """After H inner steps: reduce pseudo-gradients, outer update.

    ``axis_name`` (inside pmap/shard_map) or ``reducer`` (eager, takes
    a list of per-replica deltas — see ``reducers.gta_reduce``) control
    how replica deltas merge; with neither, single-replica outer step.
    Returns (new_params, new_state).
    """
    outer_optimizer = outer_optimizer or default_outer_optimizer()
    # pseudo-gradient: anchor - params (descent direction for optax)
    pseudo_grad = jax.tree_util.tree_map(
        lambda a, p: (a - p).astype(jnp.float32),
        state.anchor,
        params,
    )
    if axis_name is not None:
        pseudo_grad = jax.tree_util.tree_map(
            lambda g: jax.lax.pmean(g, axis_name), pseudo_grad
        )
    elif reducer is not None:
        pseudo_grad = reducer(pseudo_grad)
    updates, outer_opt_state = outer_optimizer.update(
        pseudo_grad, state.outer_opt_state, state.anchor
    )
    new_params = optax.apply_updates(state.anchor, updates)
    new_state = DiLoCoState(
        anchor=jax.tree_util.tree_map(jnp.copy, new_params),
        outer_opt_state=outer_opt_state,
        sync_count=state.sync_count + 1,
    )
    return new_params, new_state
