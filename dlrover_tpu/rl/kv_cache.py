"""Paged KV cache: a block pool + per-sequence block tables.

Reference parity: vLLM's ``BlockAllocator``/block tables (PagedAttention)
— the serving path's answer to ``rl/inference.py``'s dense
``[L, B, max_len, KV, head_dim]`` slab, which reserves worst-case
memory per *batch* and cannot admit a new sequence without recompiling
or re-allocating.  Here the cache is one fixed pool of
``block_size``-token blocks (``[L, num_blocks, block_size, KV, D]``,
the layout ``ops/paged_attention.py`` gathers), sequences own integer
block lists, and admission/eviction is pure host-side bookkeeping —
the device arrays never change shape, so the decode program compiles
exactly once.

Block 0 is the NULL block: never allocated, the scatter/gather target
for inactive lanes and unwritten table entries (always masked).

Two allocation disciplines share the pool (the scheduler picks via
``DLROVER_TPU_KV_INCREMENTAL``):

- **reservation** (PR-13, the kill-switch path): :meth:`allocate`
  reserves a sequence's worst case up front, so decode can never die
  of exhaustion — at the price of reserved-but-unfilled capacity;
- **incremental** (vLLM-style): admit on prompt blocks + a small
  headroom, :meth:`extend` the table on demand at decode time, and
  let the scheduler preempt the lowest-priority sequence when the
  pool runs dry.

**Prefix caching** rides the incremental discipline: a FULL prompt
block is content-addressed by a chained hash of its tokens
(:func:`prefix_block_keys`) and registered in a ref-counted
shared-block index, so N requests with a common system-prompt prefix
map the SAME physical blocks.  Sharing is read-only — a block is
immutable once full, so no copy-on-write is ever needed for the
full-block prefix (the partial tail block is always private).  A
shared block whose last holder frees it moves to a ref-count-gated
LRU cache (content retained for future hits) and is evicted back to
the free list only under allocation pressure, oldest first.

Accounting (the observatory's ``kv_blocks_used`` /
``kv_utilization`` gauges and the fragmentation / hit-rate lines in
``scripts/bench_serving.py`` read these):

- ``used_blocks`` / ``free_blocks`` — pool occupancy;
- ``internal_fragmentation()`` — reserved-but-unfilled token slots as
  a share of reserved capacity (block-granularity waste, the quantity
  paging keeps bounded at < ``block_size`` tokens/sequence where the
  dense slab wastes ``max_len - len`` per sequence);
- ``utilization()`` — filled cache positions as a share of the whole
  pool's capacity (the number reservation admission caps far below
  1.0 and incremental admission pushes toward it);
- ``prefix_hits`` / ``prefix_queries`` — shared-block lookups.
"""

import hashlib
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp


def blocks_needed(n_tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``n_tokens`` cache positions."""
    return -(-max(int(n_tokens), 0) // int(block_size))


def pool_can_ever_hold(num_blocks: int, block_size: int,
                       n_tokens: int) -> bool:
    """Can a pool of ``num_blocks`` (INCLUDING its null block 0) ever
    hold one sequence of ``n_tokens``?  The ONE definition of the
    incremental-mode worst-case admission guard — the scheduler's
    ``submit`` and the serving dispatcher's ``submit`` must agree, or
    an oversized request slips past the dispatcher and kills the
    replica whose scheduler then refuses it."""
    return blocks_needed(n_tokens, block_size) <= int(num_blocks) - 1


@dataclass(frozen=True)
class PagedCacheConfig:
    n_layers: int
    n_kv_heads: int
    head_dim: int
    num_blocks: int  # pool size INCLUDING the null block
    block_size: int = 16
    dtype: object = jnp.bfloat16

    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - 1  # block 0 is the null block

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` cache positions."""
        return blocks_needed(n_tokens, self.block_size)


def init_block_pool(cfg: PagedCacheConfig) -> Dict[str, jnp.ndarray]:
    """The device-side pool, stacked on the layer dim like the params
    (``[L, num_blocks, block_size, KV, head_dim]``)."""
    shape = (
        cfg.n_layers,
        cfg.num_blocks,
        cfg.block_size,
        cfg.n_kv_heads,
        cfg.head_dim,
    )
    return {
        "k": jnp.zeros(shape, dtype=cfg.dtype),
        "v": jnp.zeros(shape, dtype=cfg.dtype),
    }


def prefix_block_keys(tokens, block_size: int) -> List[str]:
    """Content keys for the FULL blocks of a token stream: key ``i``
    is a chained hash over blocks ``0..i`` (position-dependent by
    construction — two prompts share block ``i`` iff their first
    ``(i + 1) * block_size`` tokens are identical)."""
    import numpy as np

    toks = np.asarray(tokens, np.int32).reshape(-1)
    keys: List[str] = []
    h = hashlib.sha1()
    for start in range(0, toks.size - block_size + 1, block_size):
        h.update(toks[start:start + block_size].tobytes())
        keys.append(h.hexdigest())
    return keys


def region_nbytes_per_block(pool: Dict[str, jnp.ndarray]) -> int:
    """Bytes one block occupies in ONE stream (k or v) across all
    layers — the unit the ship-arena slot sizing is quoted in.  Both
    ends of a ship must agree on this number (same model config =>
    same pool shape), and it is derived from the pool itself so a
    dtype or head-dim change can never desynchronize them."""
    return int(pool["k"].nbytes // pool["k"].shape[1])


def extract_block_regions(
    pool: Dict[str, jnp.ndarray], block_ids: Sequence[int]
):
    """Pull the contiguous ``[L, n_blocks, block_size, KV, head_dim]``
    tiles for ``block_ids`` out of the device pool as host numpy
    arrays (k and v) — the prefill side of a KV block ship.  Full
    blocks are immutable, so the copy is a consistent snapshot; the
    bytes are bit-exact pool content (no dtype round trip).

    Blocks are pulled one at a time with a *traced* index
    (``dynamic_index_in_dim``) so the gather compiles once per pool
    shape and is reused for every block id and every region length —
    a fancy-index gather would recompile per distinct ``len(block_ids)``
    and stall the prefill worker's loop mid-ship."""
    import numpy as np
    from jax import lax

    tiles = [
        (
            np.asarray(
                lax.dynamic_index_in_dim(
                    pool["k"], jnp.int32(b), axis=1, keepdims=False
                )
            ),
            np.asarray(
                lax.dynamic_index_in_dim(
                    pool["v"], jnp.int32(b), axis=1, keepdims=False
                )
            ),
        )
        for b in block_ids
    ]
    return (
        np.stack([t[0] for t in tiles], axis=1),
        np.stack([t[1] for t in tiles], axis=1),
    )


def insert_block_regions(
    pool: Dict[str, jnp.ndarray],
    block_ids: Sequence[int],
    k_region,
    v_region,
) -> Dict[str, jnp.ndarray]:
    """Splice shipped block tiles into the receiving pool at
    ``block_ids`` (freshly allocated there) — the decode side of a KV
    block ship.  Returns the updated pool dict.  The regions must be
    the ``[L, n, block_size, KV, head_dim]`` layout
    :func:`extract_block_regions` produced; dtype is preserved so the
    inserted blocks are bitwise-identical attention inputs.

    Blocks are spliced one at a time with a *traced* index
    (``dynamic_update_index_in_dim``) so the scatter compiles once per
    pool shape and is reused for every block id and region length — a
    fancy-index ``.at[ids].set`` recompiles per distinct
    ``len(block_ids)``, which stalls the decode replica's token loop
    (seconds of XLA compile) the first time each prompt length adopts."""
    import numpy as np
    from jax import lax

    k = pool["k"]
    v = pool["v"]
    kr = np.asarray(k_region)
    vr = np.asarray(v_region)
    for j, bid in enumerate(block_ids):
        i = jnp.int32(bid)
        k = lax.dynamic_update_index_in_dim(
            k, jnp.asarray(kr[:, j], k.dtype), i, axis=1
        )
        v = lax.dynamic_update_index_in_dim(
            v, jnp.asarray(vr[:, j], v.dtype), i, axis=1
        )
    return {"k": k, "v": v}


class OutOfBlocksError(RuntimeError):
    """The pool cannot satisfy an allocation — admission control
    should have checked :meth:`BlockPool.can_allocate` first (or, in
    incremental mode, preempted a running sequence)."""


class DoubleFreeError(RuntimeError):
    """A block id was returned to the free list twice.  Freeing loudly
    beats corrupting the LIFO free list into handing one block to two
    sequences — the scatter/gather would silently interleave their
    K/V (e.g. an evict racing a drain-requeue)."""


@dataclass
class _SeqAlloc:
    blocks: List[int] = field(default_factory=list)
    filled_tokens: int = 0  # cache positions actually written
    shared_prefix: int = 0  # leading blocks held via the shared index


class BlockPool:
    """Host-side block accounting (free list + per-sequence tables +
    the ref-counted shared-block index).

    Pure bookkeeping — device memory is the fixed-size pool from
    :func:`init_block_pool`; this class only decides which block ids a
    sequence owns.  LIFO free list: a just-freed block is re-issued
    first, which keeps the hot working set small.
    """

    def __init__(self, cfg: PagedCacheConfig):
        self.cfg = cfg
        # block 0 reserved as the null block
        self._free: List[int] = list(range(cfg.num_blocks - 1, 0, -1))
        self._free_set = set(self._free)
        self._seqs: Dict[int, _SeqAlloc] = {}
        # shared-block index: content key <-> block id, per-block
        # refcount, and the LRU of refcount-0 cached blocks
        self._shared_by_key: Dict[str, int] = {}
        self._shared_key_of: Dict[int, str] = {}
        self._ref: Dict[int, int] = {}
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        self.alloc_count = 0
        self.free_count = 0
        self.peak_used = 0
        self.prefix_hits = 0  # full-block lookups answered shared
        self.prefix_queries = 0  # full-block lookups attempted

    # ---------------------------------------------------------- queries
    @property
    def used_blocks(self) -> int:
        """Blocks held by LIVE sequences.  Refcount-0 cached shared
        blocks are excluded — their content is retained for prefix
        hits but they are reclaimable on demand, i.e. not leaked."""
        return (
            self.cfg.usable_blocks - len(self._free) - len(self._lru)
        )

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def available_blocks(self) -> int:
        """Blocks an allocation can claim: truly free plus refcount-0
        shared blocks the LRU would evict under pressure."""
        return len(self._free) + len(self._lru)

    @property
    def cached_shared_blocks(self) -> int:
        return len(self._lru)

    @property
    def live_sequences(self) -> int:
        return len(self._seqs)

    def can_allocate(self, n_tokens: int) -> bool:
        return self.cfg.blocks_for(n_tokens) <= len(self._free)

    def blocks_of(self, seq_id: int) -> List[int]:
        return list(self._seqs[seq_id].blocks)

    def covered_tokens(self, seq_id: int) -> int:
        """Cache positions the sequence's current table can hold."""
        return len(self._seqs[seq_id].blocks) * self.cfg.block_size

    def internal_fragmentation(self) -> float:
        """Reserved-but-unfilled cache slots / reserved slots (0.0
        when nothing is allocated)."""
        reserved = sum(
            len(s.blocks) * self.cfg.block_size
            for s in self._seqs.values()
        )
        if reserved == 0:
            return 0.0
        filled = sum(s.filled_tokens for s in self._seqs.values())
        return 1.0 - filled / reserved

    def utilization(self) -> float:
        """Filled cache positions / whole-pool capacity — shared
        blocks count once (physical occupancy, capped at 1.0)."""
        cap = self.cfg.usable_blocks * self.cfg.block_size
        if cap <= 0:
            return 0.0
        filled = sum(s.filled_tokens for s in self._seqs.values())
        # shared blocks are filled once but counted by every holder;
        # subtract the duplicate holders' worth
        dup_blocks = sum(
            max(self._ref.get(b, 1) - 1, 0)
            for b in self._shared_key_of
        )
        filled -= dup_blocks * self.cfg.block_size
        return min(max(filled / cap, 0.0), 1.0)

    def prefix_hit_rate(self) -> float:
        if self.prefix_queries == 0:
            return 0.0
        return self.prefix_hits / self.prefix_queries

    def stats(self) -> Dict[str, float]:
        return {
            "used_blocks": self.used_blocks,
            "free_blocks": self.free_blocks,
            "cached_shared_blocks": self.cached_shared_blocks,
            "peak_used_blocks": self.peak_used,
            "live_sequences": self.live_sequences,
            "allocs": self.alloc_count,
            "frees": self.free_count,
            "prefix_hits": self.prefix_hits,
            "prefix_queries": self.prefix_queries,
            "prefix_hit_rate": round(self.prefix_hit_rate(), 4),
            "internal_fragmentation": round(
                self.internal_fragmentation(), 4
            ),
            "kv_utilization": round(self.utilization(), 4),
        }

    # -------------------------------------------------- free-list core
    def _push_free(self, block_id: int):
        if block_id in self._free_set:
            raise DoubleFreeError(
                f"block {block_id} freed twice: it is already on the "
                "free list (evict racing a drain-requeue?)"
            )
        if block_id in self._shared_key_of or block_id in self._lru:
            raise DoubleFreeError(
                f"block {block_id} freed while still in the shared "
                "index"
            )
        self._free.append(block_id)
        self._free_set.add(block_id)

    def _pop_free(self) -> int:
        block = self._free.pop()
        self._free_set.discard(block)
        return block

    def _evict_lru(self, need: int):
        """Reclaim up to ``need`` refcount-0 shared blocks (oldest
        first) back onto the free list."""
        while need > 0 and self._lru:
            block, _ = self._lru.popitem(last=False)
            key = self._shared_key_of.pop(block)
            self._shared_by_key.pop(key, None)
            self._ref.pop(block, None)
            self._push_free(block)
            need -= 1

    def _take_blocks(self, need: int) -> List[int]:
        if need > len(self._free):
            self._evict_lru(need - len(self._free))
        if need > len(self._free):
            raise OutOfBlocksError(
                f"need {need} blocks, {len(self._free)} free "
                f"({len(self._lru)} cached-shared)"
            )
        return [self._pop_free() for _ in range(need)]

    # ---------------------------------------------------- shared index
    def peek_prefix(self, keys: Sequence[str]) -> Tuple[int, int]:
        """How many leading keys the shared index could answer RIGHT
        NOW — side-effect free (no refcounts, no hit/query counters);
        the admission sizing probe.  Returns ``(hits, hits_in_lru)``:
        a hit currently parked in the refcount-0 LRU is NOT evictable
        capacity once acquired, so admission math must not count it
        both as a hit and as an available block."""
        n = in_lru = 0
        for key in keys:
            block = self._shared_by_key.get(key)
            if block is None:
                break
            n += 1
            if block in self._lru:
                in_lru += 1
        return n, in_lru

    def acquire_prefix(self, keys: Sequence[str]) -> List[int]:
        """Longest-prefix lookup in the shared-block index: returns
        the block ids of the leading keys already cached (refs bumped,
        removed from the LRU).  Every key attempted counts as a query;
        every answered one as a hit."""
        hit: List[int] = []
        for key in keys:
            self.prefix_queries += 1
            block = self._shared_by_key.get(key)
            if block is None:
                break
            self.prefix_hits += 1
            self._ref[block] = self._ref.get(block, 0) + 1
            self._lru.pop(block, None)
            hit.append(block)
        return hit

    def share_block(self, seq_id: int, block_index: int,
                    key: str) -> bool:
        """Promote one of ``seq_id``'s PRIVATE blocks (by index into
        its table) into the shared index under ``key`` — called by the
        scheduler the moment prefill fills a whole prompt block (full
        blocks are immutable, so sharing is safe from then on).
        Returns False when the key is already indexed (a concurrent
        identical prompt won the race; this copy stays private)."""
        if key in self._shared_by_key:
            return False
        block = self._seqs[seq_id].blocks[block_index]
        if block in self._shared_key_of:
            return False  # already shared (resumed re-prefill)
        self._shared_by_key[key] = block
        self._shared_key_of[block] = key
        self._ref[block] = self._ref.get(block, 0) + 1
        return True

    def _release_block(self, block: int):
        """Return one block at sequence-free time: shared blocks
        decref (refcount 0 -> LRU, content retained); private blocks
        go straight to the free list."""
        key = self._shared_key_of.get(block)
        if key is None:
            self._push_free(block)
            return
        ref = self._ref.get(block, 0) - 1
        if ref < 0:
            raise DoubleFreeError(
                f"shared block {block} released below refcount 0"
            )
        self._ref[block] = ref
        if ref == 0:
            self._lru[block] = None
            self._lru.move_to_end(block)

    # ------------------------------------------------------- lifecycle
    def allocate(
        self,
        seq_id: int,
        n_tokens: int,
        extra_blocks: int = 0,
        prefix_blocks: Optional[List[int]] = None,
    ) -> List[int]:
        """Reserve blocks for ``n_tokens`` cache positions (plus
        ``extra_blocks`` growth headroom).  Under reservation
        admission the scheduler passes the worst case (prompt +
        max_new) so decode can never die of pool exhaustion
        mid-flight; under incremental admission it passes the prompt
        plus a small headroom and grows on demand via :meth:`extend`.
        ``prefix_blocks`` (already acquired via
        :meth:`acquire_prefix`) become the leading table entries; only
        the remainder is newly allocated."""
        if seq_id in self._seqs:
            raise ValueError(f"sequence {seq_id} already allocated")
        prefix = list(prefix_blocks or [])
        need = max(
            self.cfg.blocks_for(n_tokens) - len(prefix), 0
        ) + max(int(extra_blocks), 0)
        try:
            blocks = self._take_blocks(need)
        except OutOfBlocksError:
            raise OutOfBlocksError(
                f"need {need} blocks for seq {seq_id}, "
                f"{len(self._free)} free"
            ) from None
        self._seqs[seq_id] = _SeqAlloc(
            blocks=prefix + blocks,
            shared_prefix=len(prefix),
        )
        self.alloc_count += need
        self.peak_used = max(self.peak_used, self.used_blocks)
        return list(self._seqs[seq_id].blocks)

    def extend(self, seq_id: int, n_blocks: int) -> List[int]:
        """Grow a live sequence's table by ``n_blocks`` (the
        incremental-allocation decode path).  Raises
        :class:`OutOfBlocksError` when the pool (free + evictable
        shared) cannot satisfy it — the scheduler then preempts."""
        alloc = self._seqs[seq_id]
        blocks = self._take_blocks(max(int(n_blocks), 0))
        alloc.blocks.extend(blocks)
        self.alloc_count += len(blocks)
        self.peak_used = max(self.peak_used, self.used_blocks)
        return blocks

    def note_filled(self, seq_id: int, filled_tokens: int):
        """Record how many cache positions the sequence has actually
        written (drives the fragmentation/utilization figures)."""
        self._seqs[seq_id].filled_tokens = int(filled_tokens)

    def free(self, seq_id: int) -> int:
        """Return a finished/evicted/preempted sequence's blocks:
        private blocks to the pool, shared blocks decref'd (a
        refcount-0 shared block parks in the LRU with its content
        intact for future prefix hits).  Raises
        :class:`DoubleFreeError` if any block would land on the free
        list twice."""
        alloc = self._seqs.pop(seq_id, None)
        if alloc is None:
            return 0
        for block in reversed(alloc.blocks):
            self._release_block(block)
        # allocs/frees count OWNERSHIP churn, symmetrically: allocs =
        # blocks this sequence newly took from the pool (acquired
        # prefix hits excluded), frees = those same blocks released
        # from its ownership — whether they land on the free list or
        # park in the LRU (a later LRU eviction moves an already-
        # released block and touches neither counter).  Under this
        # definition allocs == frees after any full drain.
        self.free_count += len(alloc.blocks) - alloc.shared_prefix
        return len(alloc.blocks)

    def table_row(
        self, seq_id: int, max_blocks: int
    ) -> Optional[List[int]]:
        """The sequence's block table padded to ``max_blocks`` with
        null-block ids (the fixed-shape row the jitted decode step
        consumes)."""
        alloc = self._seqs.get(seq_id)
        if alloc is None:
            return None
        if len(alloc.blocks) > max_blocks:
            raise ValueError(
                f"seq {seq_id} owns {len(alloc.blocks)} blocks > "
                f"table width {max_blocks}"
            )
        return alloc.blocks + [0] * (max_blocks - len(alloc.blocks))
