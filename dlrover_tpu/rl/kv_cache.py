"""Paged KV cache: a block pool + per-sequence block tables.

Reference parity: vLLM's ``BlockAllocator``/block tables (PagedAttention)
— the serving path's answer to ``rl/inference.py``'s dense
``[L, B, max_len, KV, head_dim]`` slab, which reserves worst-case
memory per *batch* and cannot admit a new sequence without recompiling
or re-allocating.  Here the cache is one fixed pool of
``block_size``-token blocks (``[L, num_blocks, block_size, KV, D]``,
the layout ``ops/paged_attention.py`` gathers), sequences own integer
block lists, and admission/eviction is pure host-side bookkeeping —
the device arrays never change shape, so the decode program compiles
exactly once.

Block 0 is the NULL block: never allocated, the scatter/gather target
for inactive lanes and unwritten table entries (always masked).

Accounting (the observatory's ``kv_blocks_used`` gauge and the
fragmentation line in ``scripts/bench_serving.py`` read these):

- ``used_blocks`` / ``free_blocks`` — pool occupancy;
- ``internal_fragmentation()`` — reserved-but-unfilled token slots as
  a share of reserved capacity (block-granularity waste, the quantity
  paging keeps bounded at < ``block_size`` tokens/sequence where the
  dense slab wastes ``max_len - len`` per sequence).
"""

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax.numpy as jnp


@dataclass(frozen=True)
class PagedCacheConfig:
    n_layers: int
    n_kv_heads: int
    head_dim: int
    num_blocks: int  # pool size INCLUDING the null block
    block_size: int = 16
    dtype: object = jnp.bfloat16

    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - 1  # block 0 is the null block

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` cache positions."""
        return -(-max(int(n_tokens), 0) // self.block_size)


def init_block_pool(cfg: PagedCacheConfig) -> Dict[str, jnp.ndarray]:
    """The device-side pool, stacked on the layer dim like the params
    (``[L, num_blocks, block_size, KV, head_dim]``)."""
    shape = (
        cfg.n_layers,
        cfg.num_blocks,
        cfg.block_size,
        cfg.n_kv_heads,
        cfg.head_dim,
    )
    return {
        "k": jnp.zeros(shape, dtype=cfg.dtype),
        "v": jnp.zeros(shape, dtype=cfg.dtype),
    }


class OutOfBlocksError(RuntimeError):
    """The pool cannot satisfy an allocation — admission control
    should have checked :meth:`BlockPool.can_allocate` first."""


@dataclass
class _SeqAlloc:
    blocks: List[int] = field(default_factory=list)
    filled_tokens: int = 0  # cache positions actually written


class BlockPool:
    """Host-side block accounting (free list + per-sequence tables).

    Pure bookkeeping — device memory is the fixed-size pool from
    :func:`init_block_pool`; this class only decides which block ids a
    sequence owns.  LIFO free list: a just-freed block is re-issued
    first, which keeps the hot working set small.
    """

    def __init__(self, cfg: PagedCacheConfig):
        self.cfg = cfg
        # block 0 reserved as the null block
        self._free: List[int] = list(range(cfg.num_blocks - 1, 0, -1))
        self._seqs: Dict[int, _SeqAlloc] = {}
        self.alloc_count = 0
        self.free_count = 0
        self.peak_used = 0

    # ---------------------------------------------------------- queries
    @property
    def used_blocks(self) -> int:
        return self.cfg.usable_blocks - len(self._free)

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def live_sequences(self) -> int:
        return len(self._seqs)

    def can_allocate(self, n_tokens: int) -> bool:
        return self.cfg.blocks_for(n_tokens) <= len(self._free)

    def blocks_of(self, seq_id: int) -> List[int]:
        return list(self._seqs[seq_id].blocks)

    def internal_fragmentation(self) -> float:
        """Reserved-but-unfilled cache slots / reserved slots (0.0
        when nothing is allocated)."""
        reserved = sum(
            len(s.blocks) * self.cfg.block_size
            for s in self._seqs.values()
        )
        if reserved == 0:
            return 0.0
        filled = sum(s.filled_tokens for s in self._seqs.values())
        return 1.0 - filled / reserved

    def stats(self) -> Dict[str, float]:
        return {
            "used_blocks": self.used_blocks,
            "free_blocks": self.free_blocks,
            "peak_used_blocks": self.peak_used,
            "live_sequences": self.live_sequences,
            "allocs": self.alloc_count,
            "frees": self.free_count,
            "internal_fragmentation": round(
                self.internal_fragmentation(), 4
            ),
        }

    # ------------------------------------------------------- lifecycle
    def allocate(self, seq_id: int, n_tokens: int) -> List[int]:
        """Reserve blocks for ``n_tokens`` cache positions.  The
        scheduler reserves a sequence's worst case (prompt + max_new)
        at admission so decode can never die of pool exhaustion
        mid-flight (reservation admission — the tradeoff is bounded
        internal fragmentation, reported above)."""
        if seq_id in self._seqs:
            raise ValueError(f"sequence {seq_id} already allocated")
        need = self.cfg.blocks_for(n_tokens)
        if need > len(self._free):
            raise OutOfBlocksError(
                f"need {need} blocks for seq {seq_id}, "
                f"{len(self._free)} free"
            )
        blocks = [self._free.pop() for _ in range(need)]
        self._seqs[seq_id] = _SeqAlloc(blocks=blocks)
        self.alloc_count += need
        self.peak_used = max(self.peak_used, self.used_blocks)
        return list(blocks)

    def note_filled(self, seq_id: int, filled_tokens: int):
        """Record how many cache positions the sequence has actually
        written (drives the fragmentation figure)."""
        self._seqs[seq_id].filled_tokens = int(filled_tokens)

    def free(self, seq_id: int) -> int:
        """Return a finished/evicted sequence's blocks to the pool."""
        alloc = self._seqs.pop(seq_id, None)
        if alloc is None:
            return 0
        self._free.extend(reversed(alloc.blocks))
        self.free_count += len(alloc.blocks)
        return len(alloc.blocks)

    def table_row(
        self, seq_id: int, max_blocks: int
    ) -> Optional[List[int]]:
        """The sequence's block table padded to ``max_blocks`` with
        null-block ids (the fixed-shape row the jitted decode step
        consumes)."""
        alloc = self._seqs.get(seq_id)
        if alloc is None:
            return None
        if len(alloc.blocks) > max_blocks:
            raise ValueError(
                f"seq {seq_id} owns {len(alloc.blocks)} blocks > "
                f"table width {max_blocks}"
            )
        return alloc.blocks + [0] * (max_blocks - len(alloc.blocks))
