"""PPO math + replay buffer, functional JAX.

Reference parity: ``atorch/atorch/rl/ppo_utils``/replay buffer — GAE
advantages, clipped surrogate policy loss with value clipping and a KL
penalty against the frozen reference policy (the RLHF objective).
"""

from typing import Dict, List, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


def compute_gae(
    rewards: jnp.ndarray,  # [T]
    values: jnp.ndarray,  # [T + 1] (bootstrap at the end)
    gamma: float = 1.0,
    lam: float = 0.95,
):
    """Generalized advantage estimation via reverse scan."""

    def step(carry, t):
        gae = carry
        delta = (
            rewards[t] + gamma * values[t + 1] - values[t]
        )
        gae = delta + gamma * lam * gae
        return gae, gae

    T = rewards.shape[0]
    _, adv_rev = jax.lax.scan(
        step, jnp.zeros(()), jnp.arange(T - 1, -1, -1)
    )
    advantages = adv_rev[::-1]
    returns = advantages + values[:-1]
    return advantages, returns


class PPOOutputs(NamedTuple):
    loss: jnp.ndarray
    policy_loss: jnp.ndarray
    value_loss: jnp.ndarray
    kl: jnp.ndarray
    clip_frac: jnp.ndarray


def ppo_loss(
    logprobs: jnp.ndarray,  # new policy logprobs [B, T]
    old_logprobs: jnp.ndarray,  # rollout-time logprobs
    ref_logprobs: jnp.ndarray,  # frozen reference policy
    values: jnp.ndarray,  # new value estimates [B, T]
    old_values: jnp.ndarray,
    advantages: jnp.ndarray,  # [B, T]
    returns: jnp.ndarray,
    mask: jnp.ndarray,  # [B, T] response-token mask
    clip_ratio: float = 0.2,
    value_clip: float = 0.2,
    vf_coef: float = 0.5,
    kl_coef: float = 0.1,
) -> PPOOutputs:
    msum = jnp.maximum(jnp.sum(mask), 1.0)

    # normalized advantages over response tokens
    amean = jnp.sum(advantages * mask) / msum
    astd = jnp.sqrt(
        jnp.sum(((advantages - amean) ** 2) * mask) / msum + 1e-8
    )
    adv = (advantages - amean) / astd

    ratio = jnp.exp(logprobs - old_logprobs)
    unclipped = ratio * adv
    clipped = jnp.clip(ratio, 1 - clip_ratio, 1 + clip_ratio) * adv
    policy_loss = -jnp.sum(
        jnp.minimum(unclipped, clipped) * mask
    ) / msum
    clip_frac = jnp.sum(
        (jnp.abs(ratio - 1.0) > clip_ratio) * mask
    ) / msum

    v_clipped = old_values + jnp.clip(
        values - old_values, -value_clip, value_clip
    )
    value_loss = 0.5 * jnp.sum(
        jnp.maximum(
            (values - returns) ** 2, (v_clipped - returns) ** 2
        )
        * mask
    ) / msum

    kl = jnp.sum((logprobs - ref_logprobs) * mask) / msum

    loss = policy_loss + vf_coef * value_loss + kl_coef * kl
    return PPOOutputs(loss, policy_loss, value_loss, kl, clip_frac)


class ReplayBuffer:
    """Rollout storage with random minibatch sampling."""

    def __init__(self, capacity: int = 4096, seed: int = 0):
        self._capacity = capacity
        self._items: List[Dict] = []
        self._rng = np.random.default_rng(seed)

    def add(self, sample: Dict):
        self._items.append(sample)
        if len(self._items) > self._capacity:
            self._items.pop(0)

    def __len__(self):
        return len(self._items)

    def clear(self):
        self._items.clear()

    def sample_batches(self, batch_size: int, epochs: int = 1):
        """Yield stacked-dict minibatches, ``epochs`` passes."""
        n = len(self._items)
        for _ in range(epochs):
            order = self._rng.permutation(n)
            for start in range(0, n - batch_size + 1, batch_size):
                idx = order[start : start + batch_size]
                batch = {}
                for key in self._items[0]:
                    batch[key] = np.stack(
                        [self._items[i][key] for i in idx]
                    )
                yield batch
