"""Cross-process generation: the legacy single-worker engine and the
continuous-batching multi-replica serving plane.

Reference parity: ``atorch/atorch/rl/inference_backend/
vllm_backend.py`` — actor weights are SHIPPED to a dedicated vLLM
serving engine, not pointer-shared — plus ``rl/ds_hybrid_engine/``
(train<->inference layout resharding).  The TPU redesign:

- a dedicated GENERATION PROCESS runs the sampler (its own jax
  runtime / mesh, its own compiled programs);
- actor weights travel over the flash-checkpoint shm substrate
  (``agent/ckpt_shm.SharedMemoryHandler``: double-buffered segment +
  SharedDict meta) — the same zero-extra-infrastructure path training
  snapshots already ride, so a policy update is ONE ``save_state``
  and N replicas adopt it from ONE segment (fan-out by attach, not
  by copy);
- train->inference RESHARDING happens at restore: the worker's params
  template carries the inference shardings, and
  ``restore_to_target`` device_puts every leaf onto them in one
  batched call (train-side layouts never leak into the generator).

Two serving shapes share this module:

- :class:`CrossProcessGenerationEngine` — the legacy single-worker
  request/queue loop (one whole batch to completion per request).
  ``DLROVER_TPU_SERVING=0`` pins exactly this path.
- :class:`ServingEngine` — N replica workers, each running the
  token-level continuous-batching scheduler (``rl/scheduler.py``)
  over a paged KV cache, behind a dispatcher with per-replica
  shm-ring request/response transport (the PR-4 zero-copy path —
  prompts and sampled tokens never pickle through a socket).
  Replicas are first-class elastic workloads: SIGUSR1/SIGTERM drains
  a replica (unfinished sequences requeue onto survivors — sampling
  is (seed, position)-pure, so a requeued tail is the same tail), a
  SIGKILL'd replica's in-flight requests redispatch automatically,
  and completions dedup by request id so every request finishes
  exactly once.  ``make_generation_engine`` picks the shape from the
  environment.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from dlrover_tpu.common.env import (
    fleet_imbalance_cap,
    fleet_min_ship_prompt,
    fleet_prefill_workers,
    fleet_ship_slots,
    gen_close_timeout_s,
    gen_timeout_s,
    serve_fleet_enabled,
    serve_obs_enabled,
    serving_enabled,
)
from dlrover_tpu.common.log import default_logger as logger

WORKER_SPEC_ENV = "DLROVER_TPU_GEN_SPEC"

# response-ring message kinds
_KIND_RESULT = 0
_KIND_READY = 1
_KIND_DRAINED = 2
_KIND_STATS = 3
# a request the replica REJECTED (scheduler refused the submit):
# the dispatcher must fail it to the caller immediately — silence
# here would block result() for the whole request timeout
_KIND_REJECT = 4
# a prefill worker finished filling a request's KV blocks and staged
# them in the ship arena: meta carries the slot + block count and
# tokens[0] the first sampled token — the dispatcher relays the
# manifest to a decode replica (disaggregated fleet only; never
# emitted with DLROVER_TPU_SERVE_FLEET=0)
_KIND_SHIP = 5
# a DRAINING replica hands one unfinished request back WITH its
# generated-so-far tail (+ per-token logprobs): the dispatcher stores
# the tail and re-dispatches with ``resume_tokens`` so the survivor
# re-prefills the whole [prompt|tail] prefix through the block-hash
# cache instead of regenerating it (flywheel layer; a SIGKILL'd
# replica can't send these — its requests redispatch fresh)
_KIND_REQUEUE = 6
_FINISH_CODES = {"length": 0, "eos": 1}
_FINISH_NAMES = {v: k for k, v in _FINISH_CODES.items()}

#: Explicit schema version of BOTH shm-ring payloads.  PR 14 silently
#: widened the response ``times`` vector 4→8 floats — a mixed-width
#: reader would have misparsed stats as garbage numbers instead of
#: failing.  v4 (this layout): request meta carries
#: [req_id, prompt_len, max_new, seed, schema_version, submit_wall_ns,
#: slo_class, tenant_hash, ship_mode, ship_slot, first_token,
#: n_blocks, route, resume_len] — the prompt buffer holds
#: [prompt|resume tail] and ``resume_lp`` the tail's per-token
#: logprobs (NaN where unknown) — and response meta carries
#: [req_id, kind, total_len, new_tokens, finish_code, weights_version,
#: schema_version, ship_slot, n_blocks] plus a ``logprobs`` f4 vector
#: (per sampled token, flywheel capture mode only; zeros otherwise).
#: ship_mode: 0 = serve
#: locally, 1 = prefill-and-ship (the replica fills the KV blocks,
#: stages them in the ship arena slot and answers _KIND_SHIP),
#: 2 = adopt-and-decode (the replica splices the staged blocks into
#: its own pool and runs a pure token loop).  Bump on ANY layout
#: change.
RING_SCHEMA_VERSION = 4

#: request ``route`` codes — how the dispatcher picked the replica;
#: the scheduler stamps the name on the request's serve_request span
_ROUTE_NAMES = {0: "least_outstanding", 1: "affinity", 2: "ship"}


def _key_digest(hex_key: str) -> int:
    """31-bit digest of one ``prefix_block_keys`` chain key — small
    enough to piggyback dozens of them in a STATS message's otherwise
    unused int32 ``tokens`` field (the per-replica shared-block index
    the affinity router matches against)."""
    return int(hex_key[:8], 16) & 0x7FFFFFFF


def _tenant_hash(tenant: str) -> int:
    """Stable cross-process tenant key (``hash()`` is salted per
    interpreter — the fair-share lanes only need distinctness)."""
    if not tenant:
        return 0
    import zlib

    return zlib.crc32(tenant.encode("utf-8", "replace")) or 1


class RingSchemaMismatch(RuntimeError):
    """A ring message written under a different payload schema than
    this reader understands (a mixed-version dispatcher/replica pair
    — e.g. a rolling upgrade that restarted only one side)."""

    def __init__(self, got: int, what: str):
        self.got = int(got)
        self.expected = RING_SCHEMA_VERSION
        super().__init__(
            f"{what} payload schema v{self.got} != reader schema "
            f"v{self.expected} — dispatcher and replica were built "
            "from different ring layouts; restart both sides on one "
            "version"
        )


def _parse_stats(times, schema_version: int) -> Dict:
    """Decode one replica STATS ``times`` vector into the stats dict
    the serving pane renders.  Refuses (typed, naming both versions)
    rather than misparse a different layout."""
    if int(schema_version) != RING_SCHEMA_VERSION:
        raise RingSchemaMismatch(int(schema_version), "replica STATS")
    return {
        "tokens_per_s": round(float(times[0]), 2),
        "queue_depth": int(times[1]),
        "kv_blocks_used": int(times[2]),
        "kv_utilization": round(float(times[3]), 4),
        "preemptions": int(times[4]),
        "prefix_hit_rate": round(float(times[5]), 4),
        "accepted_per_step": round(float(times[6]), 4),
        # flywheel adoption accounting (cumulative): how many weight
        # generations this replica actually adopted, and how many
        # SharedDict meta RPCs its adopt probe burned — the
        # generation side-segment keeps the second flat while the
        # first only moves when a publish lands
        "adoptions": int(times[10]),
        "meta_rpcs": int(times[11]),
    }


def _import_factory(path: str) -> Callable:
    """"pkg.module:attr" -> callable."""
    mod_name, _, attr = path.partition(":")
    if not attr:
        raise ValueError(
            f"factory must be 'module:callable', got {path!r}"
        )
    import importlib

    return getattr(importlib.import_module(mod_name), attr)


def tiny_llama_factory(**cfg_kwargs):
    """Built-in factory: a llama sampler whose config comes from the
    spec (tests / example).  Returns the worker contract:
    ``forward_fn``, ``params_template_fn`` (inference-sharded params
    the shm snapshot restores ONTO) and ``cfg`` (the model config the
    serving scheduler builds its paged decode programs from).  A
    ``draft`` sub-dict (flywheel speculative decode) adds
    ``draft_cfg`` + ``draft_template_fn`` for the separately-published
    drafter the scheduler runs K cheap steps of per verify."""
    import jax
    import jax.numpy as jnp

    from dlrover_tpu.models.llama import (
        LlamaConfig,
        forward,
        init_params,
    )

    def _undtype(kw):
        if isinstance(kw.get("dtype"), str):
            # the spec rides through JSON: dtype arrives as a name
            kw = dict(kw, dtype=jnp.dtype(kw["dtype"]))
        return kw

    cfg_kwargs = _undtype(dict(cfg_kwargs))
    draft_kwargs = cfg_kwargs.pop("draft", None)
    cfg = LlamaConfig(**cfg_kwargs)

    def forward_fn(params, tokens):
        return forward(params, tokens, cfg)

    def params_template_fn():
        # the template's shardings ARE the inference layout; default:
        # replicated on this process's devices.  A multi-chip serving
        # mesh would device_put leaves onto its NamedShardings here.
        return init_params(jax.random.PRNGKey(0), cfg)

    parts = {
        "forward_fn": forward_fn,
        "params_template_fn": params_template_fn,
        "cfg": cfg,
    }
    if draft_kwargs:
        draft_cfg = LlamaConfig(**_undtype(dict(draft_kwargs)))
        parts["draft_cfg"] = draft_cfg
        parts["draft_template_fn"] = lambda: init_params(
            jax.random.PRNGKey(1), draft_cfg
        )
    return parts


# --------------------------------------------------------------------------
# legacy single-worker loop (DLROVER_TPU_SERVING=0 pins this path)
# --------------------------------------------------------------------------


def _legacy_worker_loop(spec) -> int:
    import jax
    import jax.numpy as jnp

    from dlrover_tpu.agent.ckpt_shm import (
        SharedMemoryHandler,
        restore_to_target,
    )
    from dlrover_tpu.common.multi_process import SharedQueue
    from dlrover_tpu.rl.inference import JitSamplerBackend

    name = spec["name"]
    factory = _import_factory(spec["factory"])
    parts = factory(**spec.get("factory_kwargs", {}))
    backend = JitSamplerBackend(
        parts["forward_fn"],
        max_new_tokens=int(spec["max_new_tokens"]),
        temperature=float(spec.get("temperature", 1.0)),
    )
    template = parts["params_template_fn"]()

    shm = SharedMemoryHandler(rank=0, name=name)
    req = SharedQueue(f"{name}-req", create=False)
    resp = SharedQueue(f"{name}-resp", create=False)
    version = -1
    handoff_s = 0.0
    resp.put({"ready": True, "pid": os.getpid()})
    logger.info("generation worker %s ready (pid %d)", name,
                os.getpid())
    while True:
        msg = req.get()
        cmd = msg.get("cmd")
        if cmd == "stop":
            resp.put({"stopped": True})
            return 0
        if cmd != "generate":
            resp.put({"error": f"unknown cmd {cmd!r}"})
            continue
        # a bad request (ragged prompts, shape-mismatched publish)
        # must answer {"error": ...}, not kill the worker — a dead
        # worker leaves every later client call blocking to timeout
        try:
            # weight refresh: adopt the newest published snapshot.
            # restore_to_target device_puts onto the TEMPLATE's
            # shardings — this is where the train layout reshards to
            # the inference layout (ref: ds_hybrid_engine's
            # train<->infer repartition)
            t0 = time.perf_counter()
            step, arrays = shm.load_state(copy=False)
            if step > version:
                template = restore_to_target(
                    template, arrays, to_device=True, copy_host=True
                )
                jax.block_until_ready(template)
                backend.sync_weights(template)
                version = step
                handoff_s = time.perf_counter() - t0
            del arrays
            prompts = jnp.asarray(msg["prompts"])
            rng = jax.random.PRNGKey(int(msg.get("seed", 0)))
            t1 = time.perf_counter()
            tokens = np.asarray(backend.generate(prompts, rng))
            gen_s = max(time.perf_counter() - t1, 1e-9)
            new_tokens = tokens.shape[1] - prompts.shape[1]
            resp.put(
                {
                    "tokens": tokens,
                    "version": version,
                    "handoff_s": round(handoff_s, 6),
                    "gen_s": round(gen_s, 6),
                    "tokens_per_s": round(
                        tokens.shape[0] * new_tokens / gen_s, 2
                    ),
                }
            )
        except Exception as e:  # noqa: BLE001 - per-request isolation
            logger.error("generation request failed: %s", e)
            resp.put({"error": f"{type(e).__name__}: {e}"})


def worker_main() -> int:
    """Generation-process entry (``python -m
    dlrover_tpu.rl.generation_service``); spec arrives via env."""
    spec = json.loads(os.environ[WORKER_SPEC_ENV])
    if spec.get("mode") == "serve":
        return _serving_worker_loop(spec)
    return _legacy_worker_loop(spec)


class CrossProcessGenerationEngine:
    """Trainer-side handle on the generation process.

    Same surface as the in-process backends (``sync_weights`` /
    ``generate``) so PPO code swaps engines without edits; the
    difference is that ``sync_weights`` PUBLISHES the policy through
    shm (no pointer sharing) and ``generate`` is served by the worker
    process.  ``last_stats`` carries the serving metrics of the most
    recent call.
    """

    def __init__(
        self,
        factory: str,
        max_new_tokens: int,
        temperature: float = 1.0,
        factory_kwargs: Optional[Dict] = None,
        name: Optional[str] = None,
        start_timeout: float = 300.0,
    ):
        from dlrover_tpu.agent.ckpt_shm import SharedMemoryHandler
        from dlrover_tpu.common.multi_process import SharedQueue

        self._name = name or f"gen-{os.getpid()}"
        # trainer side hosts the meta service + queues (it outlives
        # worker restarts)
        self._shm = SharedMemoryHandler(
            rank=0, name=self._name, host=True
        )
        self._req = SharedQueue(f"{self._name}-req", create=True)
        self._resp = SharedQueue(f"{self._name}-resp", create=True)
        self._version = 0
        self.last_stats: Dict = {}
        self.publish_s = 0.0

        spec = {
            "name": self._name,
            "factory": factory,
            "factory_kwargs": factory_kwargs or {},
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature),
        }
        env = dict(os.environ)
        env[WORKER_SPEC_ENV] = json.dumps(spec)
        import jax

        if jax.default_backend() == "cpu":
            # tests / CPU: the worker must not grab a TPU runtime
            env.setdefault("JAX_PLATFORMS", "cpu")
        self._proc = subprocess.Popen(
            [sys.executable, "-m", "dlrover_tpu.rl.generation_service"],
            env=env,
        )
        ready = self._resp.get(timeout=start_timeout)
        if not ready.get("ready"):
            raise RuntimeError(f"generation worker failed: {ready}")
        logger.info(
            "cross-process generation engine %s up (worker pid %s)",
            self._name, ready.get("pid"),
        )

    # ------------------------------------------------------------ API
    def sync_weights(self, params) -> float:
        """Publish the actor params through the shm substrate; the
        worker adopts them before serving the next request.  Returns
        the publish (snapshot) latency in seconds."""
        self._version += 1
        t0 = time.perf_counter()
        self._shm.save_state(self._version, params)
        self.publish_s = time.perf_counter() - t0
        return self.publish_s

    def generate(self, prompts, rng=None, seed: Optional[int] = None):
        if seed is None:
            seed = 0
            if rng is not None:
                import jax

                seed = int(
                    np.asarray(jax.random.key_data(rng)).ravel()[-1]
                )
        self._req.put(
            {
                "cmd": "generate",
                "prompts": np.asarray(prompts),
                "seed": int(seed),
            }
        )
        out = self._get_response(timeout=gen_timeout_s())
        if "error" in out:
            raise RuntimeError(out["error"])
        self.last_stats = {
            k: out[k]
            for k in ("version", "handoff_s", "gen_s", "tokens_per_s")
        }
        return out["tokens"]

    def _get_response(self, timeout: float, poll: float = 2.0) -> Dict:
        """Wait for the worker's response, watching the worker process:
        a dead worker must fail the call IMMEDIATELY with its exit
        code, not block the trainer for the full queue timeout
        (ADVICE-r5: generate() after a worker crash hung 600 s)."""
        import queue as _queue

        deadline = time.time() + timeout
        while True:
            try:
                return self._resp.get(
                    timeout=min(poll, max(deadline - time.time(), 0.1))
                )
            except _queue.Empty:
                rc = self._proc.poll()
                if rc is not None:
                    # the worker may have answered and THEN exited
                    # (queue flush is async): drain once more before
                    # declaring the request dead
                    try:
                        return self._resp.get(timeout=1.0)
                    except _queue.Empty:
                        pass
                    raise RuntimeError(
                        f"generation worker {self._name} died with "
                        f"exit code {rc} while serving a request"
                    ) from None
                if time.time() >= deadline:
                    raise TimeoutError(
                        f"generation worker {self._name} gave no "
                        f"response within {timeout}s"
                    ) from None

    def close(self):
        timeout = gen_close_timeout_s()
        try:
            self._req.put({"cmd": "stop"})
            self._resp.get(timeout=timeout)
        except Exception:  # noqa: BLE001 - worker may be dead already
            pass
        if self._proc.poll() is None:
            try:
                self._proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                self._proc.kill()
        self._shm.close(unlink=True)
        self._req.close()
        self._resp.close()


# --------------------------------------------------------------------------
# shm-ring transport (PR-4 zero-copy path, serving-sized slots)
# --------------------------------------------------------------------------


def _req_spec(max_prompt: int):
    from dlrover_tpu.data.shm_dataloader import BatchSpec

    return BatchSpec(
        {
            # req_id, prompt_len, max_new, seed, schema_version,
            # submit_wall_ns (the dispatcher's wall clock at submit —
            # the request-trace anchor; same-host processes share it),
            # slo_class (0 batch / 1 interactive), tenant_hash,
            # ship_mode (0 local / 1 prefill-and-ship / 2 adopt),
            # ship_slot (arena slot, -1 none), first_token (adopt
            # only), n_blocks (adopt only), route (_ROUTE_NAMES code),
            # resume_len (generated tail carried back from a drained
            # replica; the tail rides the prompt buffer at
            # [prompt_len : prompt_len + resume_len])
            "meta": ((14,), "<i8"),
            "prompt": ((max_prompt,), "<i4"),
            # the resume tail's per-token logprobs (NaN = unknown);
            # only the first resume_len entries are meaningful
            "resume_lp": ((max_prompt,), "<f4"),
        }
    )


def _resp_spec(max_total: int):
    from dlrover_tpu.data.shm_dataloader import BatchSpec

    return BatchSpec(
        {
            # req_id, kind, total_len, new_tokens, finish_code,
            # weights_version, schema_version, ship_slot, n_blocks
            "meta": ((9,), "<i8"),
            # STATS additionally piggybacks the replica's shared-block
            # key index here: tokens[0] = K, tokens[1..K] = 31-bit
            # chain-key digests (the affinity router's per-replica
            # view; SHIP carries first_token in tokens[0])
            "tokens": ((max_total,), "<i4"),
            # RESULT/REQUEUE: per-token logprobs for the sampled tail
            # (flywheel capture mode; zeros when capture is off)
            "logprobs": ((max_total,), "<f4"),
            # RESULT: latency_s, ttft_s, worker_gen_s, tokens_per_s,
            #         tbt_p99_s, queue_wait_s (trailing spare)
            # READY:  block_region_nbytes (the ship-arena slot sizer)
            # STATS:  tokens_per_s, queue_depth, kv_blocks_used,
            #         kv_utilization, preemptions, prefix_hit_rate,
            #         accepted_tokens_per_step, ttft_p99_s,
            #         prefix_hits_total, prefix_lookups_total,
            #         adoptions_total, meta_rpcs_total
            "times": ((12,), "<f8"),
        }
    )


class _Ring:
    """Single-writer single-reader fixed-slot message ring over the
    PR-4 shm substrate (``data/shm_dataloader._ShmRing``): prompts and
    token tails move as zero-copy numpy views, never pickled.

    The slot protocol (FREE -> WRITING -> fence -> FULL) intentionally
    mirrors ``ShmBatchWriter.put`` / ``ShmDataLoader.next_batch``;
    those classes assume the CONSUMER creates the ring and block on
    reads, while serving needs creator-side writers, attach-side
    readers and non-blocking polls on both ends — if the dataloader
    grows those seams this wrapper should collapse into it."""

    def __init__(self, name: str, spec=None, num_slots: int = 8,
                 create: bool = False):
        from dlrover_tpu.data import shm_dataloader as sd

        if create:
            self._ring = sd._ShmRing(name, spec, num_slots, create=True)
        else:
            self._ring = sd._attach_ring(name)
        self._next_w = 0
        self._next_r = 0

    def try_put(self, msg: Dict[str, np.ndarray],
                timeout: float = 0.0) -> bool:
        from dlrover_tpu.data import shm_dataloader as sd

        slot = self._next_w
        deadline = time.monotonic() + timeout
        delay = 0.0002
        while self._ring.slot_state(slot) != sd.SLOT_FREE:
            if time.monotonic() >= deadline:
                return False
            delay = sd._backoff_sleep(delay)
        self._ring.set_slot_state(slot, sd.SLOT_WRITING)
        self._ring.write_slot(slot, msg)
        sd._memory_fence()
        self._ring.set_slot_state(slot, sd.SLOT_FULL)
        self._next_w = (slot + 1) % self._ring.num_slots
        return True

    def try_get(self) -> Optional[Dict[str, np.ndarray]]:
        from dlrover_tpu.data import shm_dataloader as sd

        slot = self._next_r
        if self._ring.slot_state(slot) != sd.SLOT_FULL:
            return None
        sd._memory_fence()
        msg = self._ring.read_slot(slot, copy=True)
        self._ring.set_slot_state(slot, sd.SLOT_FREE)
        self._next_r = (slot + 1) % self._ring.num_slots
        return msg

    def close(self, unlink: bool = False):
        self._ring.close(unlink=unlink)


# --------------------------------------------------------------------------
# serving replica worker
# --------------------------------------------------------------------------


def _serving_worker_loop(spec) -> int:
    """One continuous-batching replica: shm-ring requests in, shm-ring
    responses out, weights adopted from the shared publish segment,
    SIGUSR1/SIGTERM = drain (stop admitting, hand unfinished
    sequences back to the dispatcher by exiting cleanly — the
    dispatcher requeues everything it never saw complete)."""
    import jax

    from dlrover_tpu.agent.ckpt_shm import (
        SharedMemoryHandler,
        restore_to_target,
    )
    from dlrover_tpu.common.env import serve_fleet_enabled
    from dlrover_tpu.observability.events import get_event_logger
    from dlrover_tpu.observability.metrics import record_serving
    from dlrover_tpu.rl.kv_cache import region_nbytes_per_block
    from dlrover_tpu.rl.scheduler import (
        ContinuousBatchingScheduler,
        SchedulerConfig,
    )

    name = spec["name"]
    replica = int(spec["replica"])
    tag = f"{name}-r{replica}"
    fleet = serve_fleet_enabled()
    role = str(spec.get("role", "unified")) if fleet else "unified"
    if role == "prefill":
        # prefill workers are throughput devices — on a host shared
        # with decode replicas they must never steal CPU from a
        # token-latency loop, so they deprioritize themselves (the
        # decode replica preempts a mid-chunk prefill the moment it
        # has a token to produce)
        try:
            os.nice(10)
        except OSError:
            pass
    drain = {"flag": False, "reason": ""}

    def _on_signal(signum, _frame):
        drain["flag"] = True
        drain["reason"] = signal.Signals(signum).name

    for sig in (signal.SIGUSR1, signal.SIGTERM):
        signal.signal(sig, _on_signal)

    factory = _import_factory(spec["factory"])
    parts = factory(**spec.get("factory_kwargs", {}))
    cfg = parts.get("cfg")
    if cfg is None:
        raise RuntimeError(
            "serving mode needs the factory to expose 'cfg' (the "
            "model config the paged decode programs build from)"
        )
    s = spec["sched"]
    # flywheel layer (ISSUE 20): logprob capture (the trajectory
    # stream's old_logp source) and the separately-published draft
    # model — both absent from the spec under DLROVER_TPU_FLYWHEEL=0,
    # so the scheduler compiles exactly the pre-flywheel programs
    fly = spec.get("flywheel") or {}
    draft_cfg = parts.get("draft_cfg")
    scheduler = ContinuousBatchingScheduler(
        cfg,
        SchedulerConfig(
            max_slots=int(s["max_slots"]),
            block_size=int(s["block_size"]),
            num_blocks=int(s["num_blocks"]),
            max_seq_len=int(s["max_seq_len"]),
            prefill_chunk=int(s["prefill_chunk"]),
            max_new_default=int(s["max_new_default"]),
            temperature=float(s["temperature"]),
            eos_id=s.get("eos_id"),
        ),
        paged_decode_fn=parts.get("paged_decode_fn"),
        paged_prefill_fn=parts.get("paged_prefill_fn"),
        paged_verify_fn=parts.get("paged_verify_fn"),
        events=get_event_logger(),
        replica=tag,
        role=("prefill" if role == "prefill" else "unified"),
        capture_logprobs=bool(fly.get("capture")),
        draft_cfg=draft_cfg,
    )
    events = get_event_logger()
    serve_obs = serve_obs_enabled()
    ttft_hist = None
    if serve_obs:
        from dlrover_tpu.observability.metrics import Histogram

        ttft_hist = Histogram()
    # chaos seam for the observatory bench (spec["faults"], keyed by
    # replica index): "sleep_s" stalls every scheduler iteration (an
    # SLO straggler — slow but progressing), "wedge_after_tokens"
    # freezes the loop outright once N tokens were sampled (dead air —
    # outstanding work, a live process, no progress, no stats).
    # Signals still land, so drain/close stay clean.
    fault = (spec.get("faults") or {}).get(str(replica)) or {}
    fault_sleep_s = float(fault.get("sleep_s", 0.0))
    wedge_after = int(fault.get("wedge_after_tokens", 0))
    if draft_cfg is not None:
        # draft mode: the publish segment carries ONE combined
        # {"policy", "draft"} tree, restored onto a combined template.
        # Until the first publish adopts, the scheduler self-drafts
        # (sync_weights without draft params) — the random-init draft
        # template is never decoded with.
        template = {
            "policy": parts["params_template_fn"](),
            "draft": parts["draft_template_fn"](),
        }
        scheduler.sync_weights(template["policy"])
    else:
        template = parts["params_template_fn"]()
        scheduler.sync_weights(template)

    shm = SharedMemoryHandler(rank=0, name=name)
    req_ring = _Ring(f"{tag}-req")
    resp_ring = _Ring(f"{tag}-resp")
    max_total = int(s["max_seq_len"])
    version = -1
    gen_seen = -1  # newest generation-segment value acted on
    adoptions = 0  # cumulative weight adoptions (STATS payload)
    meta_rpcs = 0  # cumulative get_step meta RPCs (STATS payload)

    # --- disaggregated prefill/decode plumbing (fleet layer) -------
    # the ship arena is a dispatcher-owned shm segment of fixed-size
    # slots; both sides derive the SAME slot geometry from the sched
    # spec + this pool's per-block region size, so a staged [L,
    # n_blocks, block_size, KV, head_dim] pair round-trips bitwise
    block_bytes = region_nbytes_per_block(scheduler._pool)
    import math as _math

    ship_slot_bytes = 2 * block_bytes * _math.ceil(
        int(s["max_seq_len"]) / int(s["block_size"])
    )
    ship_arena = None
    pending_ship: Dict[int, int] = {}  # req_id -> arena slot

    def _ship_buf():
        nonlocal ship_arena
        if ship_arena is None:
            from multiprocessing import shared_memory

            ship_arena = shared_memory.SharedMemory(
                name=spec["ship_arena"]
            )
        return ship_arena.buf

    def _read_shipped(slot: int, n_blocks: int):
        """Splice source: reconstruct the staged k/v regions from the
        arena slot (k in the first half, v in the second) using this
        pool's own dtype/geometry."""
        pool_k = scheduler._pool["k"]
        lyr, _, bsz, kvh, hdim = pool_k.shape
        dt = np.dtype(pool_k.dtype)
        cnt = lyr * n_blocks * bsz * kvh * hdim
        buf = _ship_buf()
        base = slot * ship_slot_bytes
        shape = (lyr, n_blocks, bsz, kvh, hdim)
        k_r = np.frombuffer(
            buf, dtype=dt, count=cnt, offset=base
        ).reshape(shape).copy()
        v_r = np.frombuffer(
            buf, dtype=dt, count=cnt,
            offset=base + ship_slot_bytes // 2,
        ).reshape(shape).copy()
        return k_r, v_r

    def _adopt_weights():
        nonlocal version, template, gen_seen, adoptions, meta_rpcs
        # fast path: one atomic-width load off the generation
        # side-segment.  The publisher bumps it AFTER save_state
        # completes, so an unchanged value means there is nothing new
        # to adopt — zero SharedDict RPCs, zero snapshot reads.  A
        # torn publish (publisher died mid-save) never bumps it, so
        # replicas keep serving the previous generation.
        gen = shm.peek_generation()
        if gen >= 0:
            if gen <= gen_seen:
                return
        else:
            # no generation segment (pre-flywheel publisher, or
            # DLROVER_TPU_FLYWHEEL=0): the legacy meta-RPC probe
            meta_rpcs += 1
            try:
                step = shm.get_step()
            except Exception:  # noqa: BLE001 - nothing published yet
                return
            if step <= version:
                return
        try:
            step, arrays = shm.load_state(copy=False)
        except Exception:  # noqa: BLE001 - gen raced ahead of meta
            return
        if gen >= 0:
            gen_seen = gen
        if step <= version:
            return
        template = restore_to_target(
            template, arrays, to_device=True, copy_host=True
        )
        jax.block_until_ready(template)
        if draft_cfg is not None and isinstance(template, dict) \
                and "draft" in template:
            scheduler.sync_weights(
                template["policy"], template["draft"]
            )
        else:
            scheduler.sync_weights(template)
        version = step
        adoptions += 1
        del arrays

    parent_pid = os.getppid()

    def _respond(kind: int, req_id: int = -1, tokens=None,
                 new_tokens: int = 0, finish: str = "length",
                 times=(), ship_slot: int = -1, n_blocks: int = 0,
                 logprobs=None):
        """Publish one message; a RESULT (or SHIP — the request's
        only path to a decode replica) must never be silently dropped
        (the dispatcher would block its caller for the full request
        timeout on a request whose compute finished), so a full ring
        WAITS for the dispatcher to drain — giving up only when the
        dispatcher process itself is gone (we are orphaned and about
        to exit anyway).  STATS are best-effort."""
        total = 0 if tokens is None else int(tokens.size)
        buf = np.zeros((max_total,), np.int32)
        if tokens is not None:
            buf[:total] = tokens
        lp_buf = np.zeros((max_total,), np.float32)
        if logprobs is not None:
            lp = np.asarray(logprobs, np.float32).reshape(-1)
            lp_buf[: lp.size] = lp[:max_total]
        padded = np.zeros((12,), np.float64)
        padded[: len(times)] = times
        msg = {
            "meta": np.asarray(
                [req_id, kind, total, new_tokens,
                 _FINISH_CODES.get(finish, 0), version,
                 RING_SCHEMA_VERSION, ship_slot, n_blocks],
                np.int64,
            ),
            "tokens": buf,
            "logprobs": lp_buf,
            "times": padded,
        }
        while True:
            if resp_ring.try_put(
                msg, timeout=0.0 if kind == _KIND_STATS else 5.0
            ):
                return True
            if kind == _KIND_STATS:
                return False  # periodic; the next window resends
            if os.getppid() != parent_pid:
                logger.warning(
                    "replica %s orphaned (dispatcher gone): "
                    "dropping message for req %d", tag, req_id,
                )
                return False
            logger.warning(
                "replica %s: response ring full, waiting for the "
                "dispatcher to drain", tag,
            )

    def _flush_result(res):
        if ttft_hist is not None:
            ttft_hist.observe(res.stats.get("ttft_s", 0.0))
        _respond(
            _KIND_RESULT,
            req_id=res.req_id,
            tokens=res.tokens,
            new_tokens=res.new_tokens,
            finish=res.finish_reason,
            logprobs=res.logprobs,
            times=(
                res.latency_s,
                res.stats.get("ttft_s", 0.0),
                res.latency_s,
                res.new_tokens / max(res.latency_s, 1e-9),
                res.stats.get("tbt_p99_s", 0.0),
                res.stats.get("queue_wait_s", 0.0),
            ),
        )

    # READY carries the per-block region size so the dispatcher can
    # size the ship arena without instantiating the model itself
    _respond(_KIND_READY, times=(float(block_bytes),))
    logger.info("serving replica %s ready (pid %d)", tag, os.getpid())
    served = 0
    window_tokens = 0
    window_t0 = time.monotonic()
    while True:
        if drain["flag"]:
            break
        if wedge_after and scheduler.total_new_tokens >= wedge_after:
            # injected dead air: the process lives, its outstanding
            # requests never progress, no stats ever flow again
            time.sleep(0.05)
            continue
        _adopt_weights()
        if fault_sleep_s:
            time.sleep(fault_sleep_s)  # injected SLO straggler
        # admit everything queued on the ring (token-level admission
        # happens inside the scheduler)
        while True:
            msg = req_ring.try_get()
            if msg is None:
                break
            (req_id, plen, max_new, seed, ring_ver, wall_ns,
             slo_i, tenant_h, ship_mode, ship_slot, first_tok,
             n_ship, route_code, resume_len) = (
                int(v) for v in msg["meta"]
            )
            if ring_ver != RING_SCHEMA_VERSION:
                raise RingSchemaMismatch(ring_ver, "dispatch request")
            try:
                kwargs = dict(
                    max_new=max_new,
                    seed=seed,
                    req_id=req_id,
                    submit_wall=(
                        wall_ns / 1e9 if wall_ns > 0 else None
                    ),
                    slo_class=(
                        "interactive" if slo_i == 1 else "batch"
                    ),
                    tenant=(str(tenant_h) if tenant_h else ""),
                    route=_ROUTE_NAMES.get(route_code,
                                           "least_outstanding"),
                )
                if resume_len > 0:
                    # a drained replica's hand-back: the tail rides
                    # the prompt buffer past the prompt; re-prefill
                    # reuses every cached [prompt|tail] block
                    kwargs["resume_tokens"] = msg["prompt"][
                        plen:plen + resume_len
                    ]
                    kwargs["resume_logprobs"] = msg["resume_lp"][
                        :resume_len
                    ]
                if ship_mode == 1:
                    # prefill-and-ship: remember which arena slot the
                    # dispatcher reserved; the blocks stage there when
                    # the prefill completes
                    pending_ship[req_id] = ship_slot
                elif ship_mode == 2:
                    k_r, v_r = _read_shipped(ship_slot, n_ship)
                    kwargs["shipped"] = {
                        "k": k_r,
                        "v": v_r,
                        "first_token": first_tok,
                    }
                scheduler.submit(msg["prompt"][:plen], **kwargs)
            except ValueError as e:
                # belt-and-suspenders (the dispatcher validates at
                # its own submit): a malformed ring message must not
                # kill the replica — a dead replica cascades the
                # request onto the survivors — and must be ANSWERED,
                # or the caller blocks for the full request timeout
                logger.error(
                    "replica %s rejected request %d: %s",
                    tag, req_id, e,
                )
                pending_ship.pop(req_id, None)
                _respond(_KIND_REJECT, req_id=req_id)
        if scheduler.idle:
            time.sleep(0.002)
            continue
        for res in scheduler.step():
            served += 1
            window_tokens += res.new_tokens
            _flush_result(res)
        if scheduler.shipped:
            # prefill worker: stage each completed prefill's KV
            # blocks in its reserved arena slot and hand the manifest
            # to the dispatcher; the decode replica splices them in
            for rec in scheduler.shipped:
                slot = pending_ship.pop(rec["req_id"], -1)
                if slot < 0:
                    continue  # locally-submitted on a prefill role
                t0 = time.perf_counter()
                k_b = rec["k"].tobytes()
                v_b = rec["v"].tobytes()
                buf = _ship_buf()
                base = slot * ship_slot_bytes
                buf[base:base + len(k_b)] = k_b
                half = base + ship_slot_bytes // 2
                buf[half:half + len(v_b)] = v_b
                ship_s = max(time.perf_counter() - t0, 1e-9)
                nbytes = len(k_b) + len(v_b)
                events.complete(
                    "kv_ship",
                    time.time() - ship_s,
                    ship_s,
                    blocks=int(rec["n_blocks"]),
                    bytes=nbytes,
                    throughput_gbps=round(nbytes / ship_s / 1e9, 3),
                )
                from dlrover_tpu.observability.metrics import (
                    get_registry,
                )

                get_registry().inc_counter(
                    "dlrover_tpu_serving_kv_shipped_blocks_total",
                    int(rec["n_blocks"]),
                    labels={"replica": tag},
                )
                window_tokens += rec["prompt_len"]
                _respond(
                    _KIND_SHIP,
                    req_id=rec["req_id"],
                    tokens=np.asarray(
                        [rec["first_token"]], np.int32
                    ),
                    ship_slot=slot,
                    n_blocks=int(rec["n_blocks"]),
                )
            scheduler.shipped.clear()
        now = time.monotonic()
        if now - window_t0 >= 1.0:
            tps = window_tokens / (now - window_t0)
            st = scheduler.stats()
            record_serving(
                replica=tag,
                tokens_per_s=tps,
                queue_depth=scheduler.queue_depth,
                kv_blocks_used=scheduler.block_pool.used_blocks,
                kv_utilization=st["kv_utilization"],
                preemptions=st["preemptions"],
                prefix_hit_rate=st["prefix_hit_rate"],
                accepted_tokens_per_step=st["accepted_per_step"],
            )
            # the dispatcher-side serving pane reads the same numbers
            # off the response ring (best-effort); with the fleet
            # layer on, the replica's shared-block key index and its
            # cumulative prefix counters ride along — the affinity
            # router's whole view, no extra RPC
            stats_tokens = None
            if fleet:
                digs = [
                    _key_digest(k)
                    for k in list(
                        scheduler.block_pool._shared_by_key
                    )[-(max_total - 1):]
                ]
                stats_tokens = np.asarray(
                    [len(digs)] + digs, np.int32
                )
            _respond(
                _KIND_STATS,
                tokens=stats_tokens,
                times=(
                    tps,
                    float(scheduler.queue_depth),
                    float(scheduler.block_pool.used_blocks),
                    float(st["kv_utilization"]),
                    float(st["preemptions"]),
                    float(st["prefix_hit_rate"]),
                    float(st["accepted_per_step"]),
                    (
                        ttft_hist.quantile(0.99)
                        if ttft_hist is not None else 0.0
                    ),
                    float(scheduler.block_pool.prefix_hits),
                    float(scheduler.block_pool.prefix_queries),
                    float(adoptions),
                    float(meta_rpcs),
                ),
            )
            window_tokens = 0
            window_t0 = now

    # drain: stop admitting, flush what finishes inside the grace
    # window (their compute is not thrown away), then hand the rest
    # back to the dispatcher (it requeues everything not seen
    # complete); tell it we left cleanly
    from dlrover_tpu.common.env import serving_drain_grace_s

    scheduler.draining = True
    grace_deadline = time.monotonic() + serving_drain_grace_s()
    while (
        scheduler.active_count and time.monotonic() < grace_deadline
    ):
        for res in scheduler.step():
            served += 1
            _flush_result(res)
    requeued = scheduler.drain()
    for r in requeued:
        # hand each unfinished request back WITH its generated tail
        # so the survivor resumes (re-prefilling the cached prefix)
        # instead of regenerating; the dispatcher falls back to a
        # fresh dispatch for anything these messages don't cover
        tail = np.asarray(r.resume_tokens, np.int32).reshape(-1)
        _respond(
            _KIND_REQUEUE,
            req_id=r.req_id,
            tokens=tail,
            new_tokens=int(tail.size),
            logprobs=r.resume_logprobs,
        )
    _respond(_KIND_DRAINED, new_tokens=len(requeued))
    logger.info(
        "serving replica %s drained on %s: served %d, handed back %d",
        tag, drain["reason"], served, len(requeued),
    )
    if ship_arena is not None:
        ship_arena.close()
    req_ring.close()
    resp_ring.close()
    shm.close()
    return 0


# --------------------------------------------------------------------------
# dispatcher
# --------------------------------------------------------------------------


def least_outstanding(replicas):
    """Routing policy: fewest in-flight requests wins, ties broken by
    LOWEST replica id — fully deterministic whatever order the alive
    list was built in, so bench runs and the kill-one-mid-load test
    reproduce across dict/list orderings (pinned by test)."""
    return min(replicas, key=lambda r: (len(r.outstanding), r.idx))


@dataclass
class _InFlight:
    req_id: int
    prompt: np.ndarray
    max_new: int
    seed: int
    submit_t: float
    submit_wall: float = 0.0  # epoch seconds; rides the request ring
    done: threading.Event = field(default_factory=threading.Event)
    result: Optional[Dict] = None
    attempts: int = 0
    slo_class: str = "batch"
    tenant: str = ""
    digests: tuple = ()  # the prompt's chain-key digests (affinity)
    ship_slot: int = -1  # arena slot reserved for this request
    # generated-so-far tail handed back by a draining replica (or
    # supplied at submit): the next dispatch resumes instead of
    # regenerating; logprobs ride along NaN-padded where unknown
    resume_tokens: np.ndarray = field(
        default_factory=lambda: np.zeros((0,), np.int32)
    )
    resume_logprobs: np.ndarray = field(
        default_factory=lambda: np.zeros((0,), np.float32)
    )


class _Replica:
    def __init__(self, idx: int, proc, req_ring: _Ring,
                 resp_ring: _Ring, role: str = "decode"):
        self.idx = idx
        self.proc = proc
        self.req_ring = req_ring
        self.resp_ring = resp_ring
        self.role = role  # "decode" serves end-to-end; "prefill" ships
        self.outstanding: Dict[int, _InFlight] = {}
        self.ready = False
        self.alive = True
        self.draining = False  # signaled; stop routing to it
        self.drained = False  # clean-handshake confirmation arrived
        self.stats: Dict = {}  # newest _KIND_STATS payload
        self.block_bytes = 0  # per-block region size (READY payload)
        self.prefix_keys: set = set()  # newest STATS key-index digest
        self.last_prefix = (0.0, 0.0)  # cumulative (hits, lookups)


class ServingEngine:
    """The continuous-batching serving plane: N replicas behind a
    dispatcher.  ``submit``/``result`` is the streaming surface;
    ``generate`` keeps the legacy whole-batch surface so PPO rollouts
    and ``examples/generate.py --serve`` swap engines without edits.

    Elasticity: ``drain_replica`` (SIGUSR1) / ``close`` (SIGTERM)
    drain; a replica that dies ANY way hands its uncompleted requests
    back to the dispatch queue, completions dedup by request id, and
    a request that kills ``max_attempts`` replicas in a row fails
    loudly instead of poisoning the fleet forever."""

    MAX_ATTEMPTS = 3

    def __init__(
        self,
        factory: str,
        max_new_tokens: int,
        temperature: float = 1.0,
        factory_kwargs: Optional[Dict] = None,
        name: Optional[str] = None,
        num_replicas: int = 2,
        max_slots: int = 8,
        block_size: int = 16,
        num_blocks: int = 512,
        max_seq_len: int = 512,
        prefill_chunk: int = 32,
        eos_id: Optional[int] = None,
        start_timeout: float = 300.0,
        ring_slots: int = 8,
        faults: Optional[Dict] = None,
        capture_logprobs: bool = False,
    ):
        from dlrover_tpu.agent.ckpt_shm import SharedMemoryHandler
        from dlrover_tpu.common.env import flywheel_enabled
        from dlrover_tpu.common.multi_process import SOCKET_DIR_ENV
        from dlrover_tpu.observability.metrics import Histogram

        self._name = name or f"serve-{os.getpid()}"
        # pin the socket namespace for the engine's whole lifetime: a
        # replica added LATER (scale-out) must land its ring handshake
        # where the existing fleet's sockets live, even if the
        # environment moved underneath us
        self._socket_dir = os.getenv(SOCKET_DIR_ENV, "")
        self._max_new = int(max_new_tokens)
        self._max_seq_len = int(max_seq_len)
        self._shm = SharedMemoryHandler(
            rank=0, name=self._name, host=True
        )
        self._version = 0
        self.publish_s = 0.0
        self._reqs: Dict[int, _InFlight] = {}
        self._dispatch_q: deque = deque()
        self._completed: set = set()  # delivered-but-uncollected ids
        self._completed_total = 0  # lifetime counter (the status pane)
        self._lock = threading.Lock()
        self._closed = False
        self._latency = Histogram()
        # serving observatory (ISSUE 16), pinned at construction:
        # per-request SLO histograms in the registry, mirrored
        # per-replica gauges, and the ServingHealthEngine derivations
        # — all absent under DLROVER_TPU_SERVE_OBS=0
        self._serve_obs = serve_obs_enabled()
        self._health = None
        if self._serve_obs:
            from dlrover_tpu.observability.health import (
                ServingHealthEngine,
            )

            self._health = ServingHealthEngine()
        # flywheel layer (ISSUE 20), pinned at construction: logprob
        # capture (the trajectory stream's old_logp), the co-published
        # draft model (a "draft" sub-dict in factory_kwargs) and the
        # generation side-segment fast path.  DLROVER_TPU_FLYWHEEL=0
        # strips all three, reproducing the pre-flywheel plane.
        self._flywheel = flywheel_enabled()
        factory_kwargs = dict(factory_kwargs or {})
        if not self._flywheel:
            capture_logprobs = False
            factory_kwargs.pop("draft", None)
        self._capture = bool(capture_logprobs)
        self._draft_mode = bool(factory_kwargs.get("draft"))
        self._spec = {
            "mode": "serve",
            "name": self._name,
            "factory": factory,
            "factory_kwargs": factory_kwargs,
            "faults": {
                str(k): v for k, v in (faults or {}).items()
            },
            "sched": {
                "max_slots": int(max_slots),
                "block_size": int(block_size),
                "num_blocks": int(num_blocks),
                "max_seq_len": int(max_seq_len),
                "prefill_chunk": int(prefill_chunk),
                "max_new_default": int(max_new_tokens),
                "temperature": float(temperature),
                "eos_id": eos_id,
            },
        }
        if self._flywheel and (self._capture or self._draft_mode):
            self._spec["flywheel"] = {"capture": self._capture}
        # fleet layer (ISSUE 17), pinned at construction: affinity
        # routing + SLO lanes + optional prefill/decode split.  OFF
        # (DLROVER_TPU_SERVE_FLEET=0) reproduces the PR-16 dispatcher
        # exactly: least-outstanding, one class, no ship arena.
        self._fleet = serve_fleet_enabled()
        self._imbalance_cap = fleet_imbalance_cap()
        n_pref = fleet_prefill_workers() if self._fleet else 0
        # at least one decode replica must remain, whatever the env
        self._n_prefill = max(0, min(n_pref, int(num_replicas) - 1))
        self._min_ship_prompt = fleet_min_ship_prompt()
        self._ship_nslots = fleet_ship_slots()
        self._ship_arena = None
        self._ship_slot_bytes = 0
        self._ship_free: List[int] = []
        self._adopt_q: deque = deque()  # staged manifests to relay
        self._fleet_hits = 0.0  # current-window prefix hit deltas
        self._fleet_lookups = 0.0
        self._fleet_hit_rate = 0.0
        if self._n_prefill:
            self._spec["ship_arena"] = f"{self._name}-ship"
        self._next_id = 0
        self._replicas: List[_Replica] = []
        for i in range(int(num_replicas)):
            self._replicas.append(self._spawn(i))
        deadline = time.monotonic() + start_timeout
        for rep in self._replicas:
            self._await_ready(rep, deadline)
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop,
            name=f"serve-dispatch-{self._name}",
            daemon=True,
        )
        self._dispatcher.start()
        logger.info(
            "serving engine %s up: %d replica(s), %d slots each",
            self._name, len(self._replicas), max_slots,
        )

    # ----------------------------------------------------- lifecycle
    def _spawn(self, idx: int) -> _Replica:
        import contextlib

        from dlrover_tpu.common.multi_process import SOCKET_DIR_ENV

        @contextlib.contextmanager
        def pinned_dir():
            old = os.environ.get(SOCKET_DIR_ENV)
            if self._socket_dir:
                os.environ[SOCKET_DIR_ENV] = self._socket_dir
            try:
                yield
            finally:
                if old is None:
                    os.environ.pop(SOCKET_DIR_ENV, None)
                else:
                    os.environ[SOCKET_DIR_ENV] = old

        tag = f"{self._name}-r{idx}"
        with pinned_dir():
            req_ring = _Ring(
                f"{tag}-req",
                spec=_req_spec(self._max_seq_len),
                num_slots=8,
                create=True,
            )
            resp_ring = _Ring(
                f"{tag}-resp",
                spec=_resp_spec(self._max_seq_len),
                num_slots=8,
                create=True,
            )
        role = (
            "prefill"
            if self._fleet and idx < self._n_prefill else "decode"
        )
        spec = dict(self._spec, replica=idx, role=role)
        env = dict(os.environ)
        env[WORKER_SPEC_ENV] = json.dumps(spec)
        if self._socket_dir:
            env[SOCKET_DIR_ENV] = self._socket_dir
        import jax

        if jax.default_backend() == "cpu":
            env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "dlrover_tpu.rl.generation_service"],
            env=env,
        )
        return _Replica(idx, proc, req_ring, resp_ring, role=role)

    def _note_ready(self, rep: _Replica, msg):
        """READY landed: record the replica's per-block region size
        and (first READY of a disaggregated fleet) size + create the
        ship arena every prefill worker stages into."""
        rep.ready = True
        try:
            rep.block_bytes = int(float(msg["times"][0]))
        except Exception:  # noqa: BLE001 - pre-v3 payload shape
            rep.block_bytes = 0
        if (
            self._n_prefill
            and self._ship_arena is None
            and rep.block_bytes > 0
        ):
            import math
            from multiprocessing import shared_memory

            s = self._spec["sched"]
            self._ship_slot_bytes = 2 * rep.block_bytes * math.ceil(
                int(s["max_seq_len"]) / int(s["block_size"])
            )
            self._ship_arena = shared_memory.SharedMemory(
                name=self._spec["ship_arena"],
                create=True,
                size=self._ship_slot_bytes * self._ship_nslots,
            )
            self._ship_free = list(range(self._ship_nslots))

    def _await_ready(self, rep: _Replica, deadline: float):
        while time.monotonic() < deadline:
            msg = rep.resp_ring.try_get()
            if msg is not None and int(msg["meta"][1]) == _KIND_READY:
                self._note_ready(rep, msg)
                return
            if rep.proc.poll() is not None:
                raise RuntimeError(
                    f"serving replica {rep.idx} died during startup "
                    f"(exit {rep.proc.returncode})"
                )
            time.sleep(0.01)
        raise TimeoutError(
            f"serving replica {rep.idx} not ready in time"
        )

    # ----------------------------------------------------------- API
    def sync_weights(self, params, draft_params=None) -> float:
        """One shm publish; every replica adopts it between scheduler
        iterations (fan-out by attach — N readers, one segment).  In
        draft mode (a ``draft`` sub-dict in ``factory_kwargs``) the
        policy and the drafter co-publish as ONE combined tree —
        ``draft_params`` is then required every call, since replicas
        restore onto a combined template.  With the flywheel layer on
        the generation side-segment is bumped AFTER the save
        completes, so replicas detect the new snapshot with one
        atomic-width load instead of a meta RPC per iteration — and a
        publisher killed mid-save never bumps it (replicas keep the
        previous generation)."""
        if self._draft_mode:
            if draft_params is None:
                raise ValueError(
                    "draft mode: sync_weights needs draft_params "
                    "(replicas restore a combined {'policy', "
                    "'draft'} tree)"
                )
            params = {"policy": params, "draft": draft_params}
        elif draft_params is not None:
            raise ValueError(
                "draft_params given but the engine was not built "
                "with a 'draft' factory sub-config"
            )
        self._version += 1
        t0 = time.perf_counter()
        self._shm.save_state(self._version, params)
        if self._flywheel:
            self._shm.publish_generation(self._version)
        self.publish_s = time.perf_counter() - t0
        return self.publish_s

    def submit(self, prompt, max_new: Optional[int] = None,
               seed: int = 0, slo_class: str = "batch",
               tenant: str = "", resume_tokens=None,
               resume_logprobs=None) -> int:
        """Queue one prompt; returns the request id.  ``slo_class``
        ("interactive" gets the reserved decode-slot lanes and
        preempts last) and ``tenant`` (the fair-share key within a
        class) only act with the fleet layer on.  ``resume_tokens``
        (a previously generated tail — e.g. carried across an engine
        restart) makes the replica re-prefill [prompt|tail] through
        its block-hash cache and continue from there instead of
        regenerating; ``resume_logprobs`` optionally carries the
        tail's captured logprobs (NaN-padded where unknown)."""
        if self._closed:
            raise RuntimeError("serving engine is closed")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("prompt must hold at least one token")
        max_new = int(
            self._max_new if max_new is None else max_new
        )
        if max_new < 1:
            raise ValueError("max_new must be >= 1")
        resume = (
            np.asarray(resume_tokens, np.int32).reshape(-1)
            if resume_tokens is not None
            else np.zeros((0,), np.int32)
        )
        if resume.size >= max_new:
            raise ValueError(
                f"resume tail of {resume.size} leaves no room under "
                f"max_new {max_new}"
            )
        rlp = np.full((resume.size,), np.nan, np.float32)
        if resume_logprobs is not None and resume.size:
            got = np.asarray(
                resume_logprobs, np.float32
            ).reshape(-1)[: resume.size]
            rlp[: got.size] = got
        if prompt.size + max_new > self._max_seq_len:
            raise ValueError(
                f"prompt {prompt.size} + max_new {max_new} exceeds "
                f"max_seq_len {self._max_seq_len}"
            )
        # the replica scheduler's incremental-mode pool guard,
        # enforced HERE with the SAME definition
        # (kv_cache.pool_can_ever_hold): a request whose worst case
        # exceeds a replica's whole pool would otherwise be refused
        # inside the worker — answered as a rejection, but only after
        # burning a dispatch — so fail it at the front door
        from dlrover_tpu.common.env import kv_incremental_enabled
        from dlrover_tpu.rl.kv_cache import pool_can_ever_hold

        s = self._spec["sched"]
        if kv_incremental_enabled() and not pool_can_ever_hold(
            int(s["num_blocks"]), int(s["block_size"]),
            prompt.size + max_new,
        ):
            raise ValueError(
                f"prompt {prompt.size} + max_new {max_new} exceeds "
                f"the replica pool of {int(s['num_blocks']) - 1} "
                "blocks"
            )
        digests: tuple = ()
        if getattr(self, "_fleet", False):
            # the prompt's chain-key digests are the affinity
            # router's match input — computed once, at the front door
            from dlrover_tpu.rl.kv_cache import prefix_block_keys

            digests = tuple(
                _key_digest(k)
                for k in prefix_block_keys(
                    prompt, int(s["block_size"])
                )[:64]
            )
        with self._lock:
            req_id = self._next_id
            self._next_id += 1
            inflight = _InFlight(
                req_id=req_id,
                prompt=prompt,
                max_new=max_new,
                seed=int(seed),
                submit_t=time.monotonic(),
                submit_wall=time.time(),
                slo_class=(
                    "interactive"
                    if slo_class == "interactive" else "batch"
                ),
                tenant=str(tenant),
                digests=digests,
                resume_tokens=resume,
                resume_logprobs=rlp,
            )
            self._reqs[req_id] = inflight
            self._dispatch_q.append(req_id)
        return req_id

    def result(self, req_id: int,
               timeout: Optional[float] = None) -> Dict:
        """Block for one request's completion; returns
        ``{"tokens", "finish_reason", "latency_s", ...}``."""
        timeout = gen_timeout_s() if timeout is None else timeout
        req = self._reqs.get(req_id)
        if req is None:
            raise KeyError(f"unknown request id {req_id}")
        if not req.done.wait(timeout=timeout):
            raise TimeoutError(
                f"request {req_id} not completed within {timeout}s "
                f"({self._alive_count()} replica(s) alive)"
            )
        res = req.result
        # collection point: a delivered result leaves the engine's
        # bookkeeping (an unbounded serving lifetime must not retain
        # every prompt/tail ever served); late duplicates still land
        # harmlessly — _complete finds no pending request
        self._reqs.pop(req_id, None)
        with self._lock:
            self._completed_total += 1
            self._completed.discard(req_id)
        if res.get("error"):
            raise RuntimeError(res["error"])
        return res

    def generate(self, prompts, rng=None, seed: Optional[int] = None):
        """Legacy whole-batch surface: [B, P] in, [B, P + max_new]
        out.  Per-row sampling seeds derive from ``seed`` + row."""
        if seed is None:
            seed = 0
            if rng is not None:
                import jax

                seed = int(
                    np.asarray(jax.random.key_data(rng)).ravel()[-1]
                )
        prompts = np.asarray(prompts, np.int32)
        ids = [
            self.submit(row, max_new=self._max_new,
                        seed=int(seed) + i * 1000003)
            for i, row in enumerate(prompts)
        ]
        rows = []
        width = prompts.shape[1] + self._max_new
        for rid in ids:
            res = self.result(rid)
            row = np.zeros((width,), np.int32)
            toks = res["tokens"][:width]
            row[: toks.size] = toks
            rows.append(row)
        return np.stack(rows)

    # ------------------------------------------------------ elasticity
    def drain_replica(self, idx: int, sig: int = signal.SIGUSR1):
        """PR-9 drain protocol: SIGUSR1 (or SIGTERM — same handler)
        -> the replica stops admitting and its unfinished sequences
        requeue onto survivors.  The dispatcher stops routing to it
        IMMEDIATELY — a request dispatched into the drain window
        would only burn one of its redispatch attempts."""
        rep = self._replicas[idx]
        rep.draining = True
        if rep.proc.poll() is None:
            rep.proc.send_signal(sig)

    def kill_replica(self, idx: int):
        """Chaos arm: hard-kill (the crash path — requests redispatch
        exactly as on drain, minus the clean handshake)."""
        rep = self._replicas[idx]
        if rep.proc.poll() is None:
            rep.proc.send_signal(signal.SIGKILL)

    def add_replica(self, wait_ready: bool = True,
                    timeout: float = 300.0) -> int:
        """Elastic scale-out: spawn one more replica; the dispatcher
        starts routing to it the moment its READY lands.  Returns the
        new replica index."""
        if self._closed:
            raise RuntimeError("serving engine is closed")
        rep = self._spawn(len(self._replicas))
        self._replicas.append(rep)
        if wait_ready:
            deadline = time.monotonic() + timeout
            # the dispatcher thread owns the response rings now; wait
            # on the flag it flips, not on the ring itself
            while not rep.ready:
                if rep.proc.poll() is not None:
                    raise RuntimeError(
                        f"replica {rep.idx} died during scale-out "
                        f"(exit {rep.proc.returncode})"
                    )
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"replica {rep.idx} not ready in {timeout}s"
                    )
                time.sleep(0.01)
        return rep.idx

    def _alive_count(self) -> int:
        return sum(1 for r in self._replicas if r.alive)

    # ------------------------------------------------------ dispatcher
    def _free_ship_slot(self, req_id: int):
        """Return a request's arena slot to the free list (completion,
        rejection, or a death-requeue that re-dispatches it fresh)."""
        req = self._reqs.get(req_id)
        if req is not None and req.ship_slot >= 0:
            self._ship_free.append(req.ship_slot)
            req.ship_slot = -1

    def _complete(self, req_id: int, result: Dict):
        with self._lock:
            if req_id in self._completed:
                return  # dedup: drain/crash races can answer twice
            self._completed.add(req_id)
        self._free_ship_slot(req_id)
        req = self._reqs.get(req_id)
        if req is None:
            return
        req.result = result
        if "latency_s" in result:
            self._latency.observe(result["latency_s"])
        req.done.set()

    def _handle_responses(self, rep: _Replica) -> int:
        n = 0
        while True:
            msg = rep.resp_ring.try_get()
            if msg is None:
                return n
            n += 1
            meta = msg["meta"]
            kind = int(meta[1])
            if kind == _KIND_DRAINED:
                rep.drained = True
                rep.draining = True
                self._retire_replica_series(rep)
                continue
            if kind == _KIND_READY:
                self._note_ready(rep, msg)
                continue
            if kind == _KIND_STATS:
                rep.stats = _parse_stats(msg["times"], meta[6])
                if self._fleet:
                    # the piggybacked shared-block key index + the
                    # fleet hit-rate deltas (cumulative counters so a
                    # dropped STATS window loses nothing)
                    k = int(msg["tokens"][0])
                    rep.prefix_keys = {
                        int(x) for x in msg["tokens"][1:1 + k]
                    }
                    hits = float(msg["times"][8])
                    looks = float(msg["times"][9])
                    ph, pl = rep.last_prefix
                    if hits >= ph and looks >= pl:
                        self._fleet_hits += hits - ph
                        self._fleet_lookups += looks - pl
                    rep.last_prefix = (hits, looks)
                if self._serve_obs:
                    rep.stats["ttft_p99_s"] = round(
                        float(msg["times"][7]), 4
                    )
                    if self._health is not None:
                        self._health.note_stats(rep.idx, rep.stats)
                continue
            if kind == _KIND_SHIP:
                # a prefill worker staged this request's KV blocks:
                # hand the manifest to a decode replica (next pump)
                req_id = int(meta[0])
                rep.outstanding.pop(req_id, None)
                self._adopt_q.append(
                    (req_id, int(meta[7]), int(meta[8]),
                     int(msg["tokens"][0]))
                )
                if self._health is not None:
                    # a ship IS the prefill worker's completion
                    self._health.note_ship(rep.idx)
                continue
            if kind == _KIND_REQUEUE:
                # a draining replica handed this request back with
                # its generated tail: store the tail and requeue —
                # the next dispatch resumes from it.  Popping the
                # request from ``outstanding`` here keeps the later
                # death-requeue from double-queueing it.
                req_id = int(meta[0])
                rep.outstanding.pop(req_id, None)
                req = self._reqs.get(req_id)
                if req is None or req_id in self._completed:
                    continue
                n_tail = int(meta[3])
                req.resume_tokens = (
                    msg["tokens"][:n_tail].astype(np.int32).copy()
                )
                req.resume_logprobs = (
                    msg["logprobs"][:n_tail].copy()
                )
                self._free_ship_slot(req_id)
                with self._lock:
                    self._dispatch_q.appendleft(req_id)
                continue
            if kind == _KIND_REJECT:
                req_id = int(meta[0])
                rep.outstanding.pop(req_id, None)
                self._complete(
                    req_id,
                    {
                        "error": (
                            f"request {req_id} rejected by replica "
                            f"{rep.idx} (scheduler refused the "
                            "submit — see the replica log)"
                        )
                    },
                )
                continue
            if kind != _KIND_RESULT:
                continue
            req_id = int(meta[0])
            total = int(meta[2])
            rep.outstanding.pop(req_id, None)
            req = self._reqs.get(req_id)
            latency = (
                time.monotonic() - req.submit_t if req else 0.0
            )
            result = {
                "tokens": msg["tokens"][:total].copy(),
                "new_tokens": int(meta[3]),
                "finish_reason": _FINISH_NAMES.get(
                    int(meta[4]), "length"
                ),
                "version": int(meta[5]),
            }
            if self._capture:
                result["logprobs"] = (
                    msg["logprobs"][: int(meta[3])].copy()
                )
            self._complete(
                req_id,
                {
                    **result,
                    "latency_s": latency,
                    "worker_latency_s": float(msg["times"][0]),
                    "ttft_s": float(msg["times"][1]),
                    "tbt_p99_s": float(msg["times"][4]),
                    "queue_wait_s": float(msg["times"][5]),
                    "replica": rep.idx,
                },
            )
            if self._serve_obs:
                from dlrover_tpu.observability.metrics import (
                    record_serving_latency,
                )

                ttft = float(msg["times"][1])
                tbt = float(msg["times"][4])
                qwait = float(msg["times"][5])
                record_serving_latency(
                    replica=str(rep.idx),
                    ttft_s=ttft,
                    tbt_p99_s=tbt,
                    e2e_s=latency,
                    queue_wait_s=qwait,
                )
                if self._health is not None:
                    self._health.note_result(
                        rep.idx, ttft_s=ttft, tbt_p99_s=tbt,
                        e2e_s=latency, queue_wait_s=qwait,
                    )

    def _retire_replica_series(self, rep: _Replica):
        """Zero-and-drop a dead/drained replica's per-replica series
        (the mirrored gauges AND the SLO histograms) from this
        process's registry: a frozen last value on ``/metrics`` reads
        as a live replica — absence reads as the death it is."""
        if not self._serve_obs:
            return
        try:
            from dlrover_tpu.observability.metrics import get_registry

            get_registry().retire_series({"replica": str(rep.idx)})
        except Exception as e:  # noqa: BLE001 - never block dispatch
            logger.warning(
                "serving series retirement failed for replica %d: %s",
                rep.idx, e,
            )

    def _handle_death(self, rep: _Replica):
        rep.alive = False
        self._retire_replica_series(rep)
        rc = rep.proc.returncode
        requeue = [
            rid for rid in rep.outstanding
            if rid not in self._completed
        ]
        rep.outstanding.clear()
        if requeue:
            logger.warning(
                "serving replica %d exited (rc=%s): requeueing %d "
                "in-flight request(s)", rep.idx, rc, len(requeue),
            )
        for rid in requeue:
            # a requeued request re-dispatches fresh; its staged
            # blocks (if any) die with the reservation
            self._free_ship_slot(rid)
        with self._lock:
            for rid in reversed(requeue):
                self._dispatch_q.appendleft(rid)

    def _req_msg(self, req: _InFlight, ship_mode: int = 0,
                 ship_slot: int = -1, first_token: int = -1,
                 n_blocks: int = 0, route: int = 0) -> Dict:
        """One v4 request-ring payload."""
        resume = req.resume_tokens
        n_resume = int(resume.size)
        prompt_buf = np.zeros((self._max_seq_len,), np.int32)
        prompt_buf[: req.prompt.size] = req.prompt
        lp_buf = np.zeros((self._max_seq_len,), np.float32)
        if n_resume:
            prompt_buf[
                req.prompt.size:req.prompt.size + n_resume
            ] = resume
            lp = np.full((n_resume,), np.nan, np.float32)
            got = req.resume_logprobs[:n_resume]
            lp[: got.size] = got
            lp_buf[:n_resume] = lp
        return {
            "meta": np.asarray(
                [req.req_id, req.prompt.size, req.max_new, req.seed,
                 RING_SCHEMA_VERSION, int(req.submit_wall * 1e9),
                 1 if req.slo_class == "interactive" else 0,
                 _tenant_hash(req.tenant), ship_mode, ship_slot,
                 first_token, n_blocks, route, n_resume],
                np.int64,
            ),
            "prompt": prompt_buf,
            "resume_lp": lp_buf,
        }

    def _route(self, req: _InFlight, targets: List[_Replica]):
        """Pick the serving replica: deepest matching prefix chain
        (each replica's shared-block key index rides its STATS
        piggyback) among replicas within ``imbalance_cap`` of the
        least-loaded — affinity must never starve a replica — else
        the PR-13 least-outstanding rule.  Returns ``(replica,
        route_code)``."""
        if not self._fleet or not req.digests or len(targets) < 2:
            return least_outstanding(targets), 0
        floor = min(len(r.outstanding) for r in targets)
        best, best_depth = None, 0
        for r in sorted(
            targets, key=lambda r: (len(r.outstanding), r.idx)
        ):
            if len(r.outstanding) > floor + self._imbalance_cap:
                continue
            depth = 0
            for d in req.digests:
                if d not in r.prefix_keys:
                    break
                depth += 1
            if depth > best_depth:
                best, best_depth = r, depth
        if best is not None:
            return best, 1
        return least_outstanding(targets), 0

    def _dispatch_loop(self):
        from dlrover_tpu.observability.metrics import record_serving

        self._last_gauges = 0.0
        while not self._closed:
            try:
                moved = self._dispatch_once(record_serving)
            except Exception as e:  # noqa: BLE001 - a dead dispatcher
                # thread wedges EVERY caller; log and keep pumping
                logger.error("serving dispatcher error: %s", e)
                moved = 0
            if not moved:
                time.sleep(0.002)

    def _dispatch_once(self, record_serving) -> int:
        """One pump: drain responses, detect deaths, route the queue,
        refresh gauges.  Returns how much moved (0 = idle tick)."""
        moved = 0
        for rep in self._replicas:
            if not rep.alive:
                continue
            moved += self._handle_responses(rep)
            if rep.proc.poll() is not None:
                # late responses may still sit in the ring
                moved += self._handle_responses(rep)
                self._handle_death(rep)
        alive = [
            r for r in self._replicas
            if r.alive and r.ready and not r.draining
        ]
        if self._fleet and self._n_prefill:
            prefill_alive = [r for r in alive if r.role == "prefill"]
            targets = [r for r in alive if r.role != "prefill"]
        else:
            prefill_alive = []
            targets = alive
        # relay staged manifests first: a parked manifest holds an
        # arena slot and its request's clock has been running since
        # submit — the decode replica splices the blocks and starts a
        # pure token loop
        while self._adopt_q and targets:
            req_id, slot, n_blocks, first = self._adopt_q[0]
            if req_id in self._completed or req_id not in self._reqs:
                self._adopt_q.popleft()
                self._free_ship_slot(req_id)
                continue
            req = self._reqs[req_id]
            rep = least_outstanding(targets)
            ok = rep.req_ring.try_put(
                self._req_msg(req, ship_mode=2, ship_slot=slot,
                              first_token=first, n_blocks=n_blocks,
                              route=2),
                timeout=0.02,
            )
            if not ok:
                break  # ring full; retry next pump
            self._adopt_q.popleft()
            rep.outstanding[req_id] = req
            moved += 1
        while self._dispatch_q and targets:
            with self._lock:
                if not self._dispatch_q:
                    break
                req_id = self._dispatch_q.popleft()
            if req_id in self._completed:
                continue
            req = self._reqs[req_id]
            req.attempts += 1
            if req.attempts > self.MAX_ATTEMPTS:
                self._complete(
                    req_id,
                    {
                        "error": (
                            f"request {req_id} failed after "
                            f"{self.MAX_ATTEMPTS} dispatch "
                            "attempts (replicas keep dying)"
                        )
                    },
                )
                continue
            use_ship = (
                prefill_alive
                and self._ship_arena is not None
                and self._ship_free
                and req.prompt.size >= self._min_ship_prompt
                # a resumed request's tail predates any shipped
                # blocks; serve it end-to-end on a decode replica
                and not req.resume_tokens.size
            )
            if use_ship:
                slot = self._ship_free.pop()
                rep = least_outstanding(prefill_alive)
                ok = rep.req_ring.try_put(
                    self._req_msg(req, ship_mode=1, ship_slot=slot,
                                  route=2),
                    timeout=0.02,
                )
                if not ok:
                    self._ship_free.append(slot)
                    req.attempts -= 1  # ring full is not a failure
                    with self._lock:
                        self._dispatch_q.appendleft(req_id)
                    break
                req.ship_slot = slot
            else:
                rep, route = self._route(req, targets)
                ok = rep.req_ring.try_put(
                    self._req_msg(req, route=route), timeout=0.02,
                )
                if not ok:
                    req.attempts -= 1  # ring full is not a failure
                    with self._lock:
                        self._dispatch_q.appendleft(req_id)
                    break
            rep.outstanding[req_id] = req
            moved += 1
        now = time.monotonic()
        if now - self._last_gauges >= 1.0:
            self._last_gauges = now
            record_serving(
                replica="dispatcher",
                tokens_per_s=None,
                queue_depth=len(self._dispatch_q),
                kv_blocks_used=None,
                p99_latency_s=self._latency.quantile(0.99),
            )
            if self._fleet:
                # fleet-level prefix hit rate: windowed over the
                # STATS deltas accumulated since the last tick with
                # lookups in it (an idle window keeps the last value
                # instead of flapping to 0)
                if self._fleet_lookups > 0:
                    self._fleet_hit_rate = (
                        self._fleet_hits / self._fleet_lookups
                    )
                    self._fleet_hits = 0.0
                    self._fleet_lookups = 0.0
                record_serving(
                    replica="fleet",
                    tokens_per_s=None,
                    queue_depth=None,
                    kv_blocks_used=None,
                    prefix_hit_rate=self._fleet_hit_rate,
                )
            if self._serve_obs:
                # mirror each live replica's newest STATS into THIS
                # process's registry so the engine's /metrics carries
                # the fleet (the per-replica series retirement on
                # death/drain acts here)
                for rep in self._replicas:
                    if not rep.alive or rep.drained or not rep.stats:
                        continue
                    st = rep.stats
                    record_serving(
                        replica=str(rep.idx),
                        tokens_per_s=st.get("tokens_per_s"),
                        queue_depth=st.get("queue_depth"),
                        kv_blocks_used=st.get("kv_blocks_used"),
                        kv_utilization=st.get("kv_utilization"),
                        preemptions=st.get("preemptions"),
                        prefix_hit_rate=st.get("prefix_hit_rate"),
                        accepted_tokens_per_step=st.get(
                            "accepted_per_step"
                        ),
                    )
        if self._health is not None:
            # internally throttled to the derivation interval
            self._health.evaluate(
                [
                    {
                        "idx": r.idx,
                        "alive": r.alive,
                        "drained": r.drained,
                        "outstanding": len(r.outstanding),
                        "role": r.role,
                        **r.stats,
                    }
                    for r in self._replicas
                ]
            )
        return moved

    # --------------------------------------------------------- status
    def _slo_quantile(self, metric: str, q: float) -> float:
        """Fleet quantile of one registry SLO histogram, merged across
        every ``replica`` series (identical bucket bounds — counts
        sum)."""
        from dlrover_tpu.observability.metrics import (
            Histogram,
            get_registry,
        )

        series = get_registry().histogram_series(metric)
        merged = None
        for hist in series.values():
            if merged is None:
                merged = Histogram(hist.bounds)
            if merged.bounds != hist.bounds:
                continue  # foreign layout; never ours
            for i, c in enumerate(hist.counts):
                merged.counts[i] += c
            merged.count += hist.count
            merged.sum += hist.sum
        return merged.quantile(q) if merged is not None else 0.0

    def status(self) -> Dict:
        """The serving pane: what ``scripts/top.py`` renders and the
        bench snapshots.  With the observatory on, ``slo`` carries the
        fleet quantiles off the registry histograms and ``health`` the
        ServingHealthEngine's newest per-replica derivations; both
        keys are ABSENT under DLROVER_TPU_SERVE_OBS=0 (pinned)."""
        out = {
            "replicas": [
                dict(
                    dict(
                        {
                            "idx": r.idx,
                            "alive": r.alive,
                            "drained": r.drained,
                            "outstanding": len(r.outstanding),
                        },
                        # the role column only exists when the fleet
                        # layer could have split roles (OFF pins the
                        # PR-16 row shape exactly)
                        **({"role": r.role} if self._fleet else {}),
                    ),
                    **r.stats,
                )
                for r in self._replicas
            ],
            "queue_depth": len(self._dispatch_q),
            "completed": self._completed_total + len(self._completed),
            "p50_latency_s": round(self._latency.quantile(0.5), 4),
            "p99_latency_s": round(self._latency.quantile(0.99), 4),
            "version": self._version,
        }
        if self._serve_obs:
            out["slo"] = {
                "ttft_p99_s": round(self._slo_quantile(
                    "dlrover_tpu_serving_ttft_seconds", 0.99
                ), 4),
                "tbt_p99_s": round(self._slo_quantile(
                    "dlrover_tpu_serving_tbt_seconds", 0.99
                ), 4),
                "e2e_p99_s": round(self._slo_quantile(
                    "dlrover_tpu_serving_e2e_seconds", 0.99
                ), 4),
                "queue_wait_p99_s": round(self._slo_quantile(
                    "dlrover_tpu_serving_queue_wait_seconds", 0.99
                ), 4),
            }
            if self._fleet:
                out["slo"]["fleet_prefix_hit_rate"] = round(
                    self._fleet_hit_rate, 4
                )
            if self._health is not None:
                out["health"] = self._health.snapshot()
        return out

    def close(self):
        if self._closed:
            return
        timeout = gen_close_timeout_s()
        for rep in self._replicas:
            if rep.proc.poll() is None:
                rep.proc.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + timeout
        for rep in self._replicas:
            remain = max(deadline - time.monotonic(), 0.1)
            try:
                rep.proc.wait(timeout=remain)
            except subprocess.TimeoutExpired:
                rep.proc.kill()
        self._closed = True
        if self._dispatcher.is_alive():
            self._dispatcher.join(timeout=5.0)
        for rep in self._replicas:
            rep.req_ring.close(unlink=True)
            rep.resp_ring.close(unlink=True)
        if self._ship_arena is not None:
            try:
                self._ship_arena.close()
                self._ship_arena.unlink()
            except Exception:  # noqa: BLE001 - already gone is fine
                pass
        self._shm.close(unlink=True)


def make_generation_engine(
    factory: str,
    max_new_tokens: int,
    **kwargs,
):
    """The serving-plane selector: :class:`ServingEngine` (continuous
    batching, multi-replica) unless ``DLROVER_TPU_SERVING=0`` pins the
    legacy single-worker request/queue loop.  Extra kwargs route to
    whichever engine is chosen (unknown ones are dropped for the
    legacy engine, whose surface is frozen)."""
    if serving_enabled():
        return ServingEngine(factory, max_new_tokens, **kwargs)
    legacy_keys = (
        "temperature", "factory_kwargs", "name", "start_timeout",
    )
    legacy_kwargs = {
        k: v for k, v in kwargs.items() if k in legacy_keys
    }
    dropped = sorted(set(kwargs) - set(legacy_kwargs))
    if dropped:
        logger.info(
            "DLROVER_TPU_SERVING=0: legacy engine ignores %s",
            dropped,
        )
    return CrossProcessGenerationEngine(
        factory, max_new_tokens, **legacy_kwargs
    )


if __name__ == "__main__":
    sys.exit(worker_main())
