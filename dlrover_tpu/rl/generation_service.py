"""Cross-process RLHF generation engine.

Reference parity: ``atorch/atorch/rl/inference_backend/
vllm_backend.py`` — actor weights are SHIPPED to a dedicated vLLM
serving engine, not pointer-shared — plus ``rl/ds_hybrid_engine/``
(train<->inference layout resharding).  The TPU redesign:

- a dedicated GENERATION PROCESS runs the sampler (its own jax
  runtime / mesh, its own compiled programs);
- actor weights travel over the flash-checkpoint shm substrate
  (``agent/ckpt_shm.SharedMemoryHandler``: double-buffered segment +
  SharedDict meta) — the same zero-extra-infrastructure path training
  snapshots already ride, so a policy update is ONE ``save_state``;
- train->inference RESHARDING happens at restore: the worker's params
  template carries the inference shardings, and
  ``restore_to_target`` device_puts every leaf onto them in one
  batched call (train-side layouts never leak into the generator);
- requests/responses ride ``common/multi_process.SharedQueue``
  (unix-socket, crash-isolated), and every response carries the
  serving stats the reference's engine exposes: weight-handoff
  latency, generation seconds, tokens/s, weight version.

The in-process backends (``rl/inference.py``) remain for co-located
generation; this module is the serving-engine form.
"""

import json
import os
import subprocess
import sys
import time
from typing import Callable, Dict, Optional

import numpy as np

from dlrover_tpu.common.log import default_logger as logger

WORKER_SPEC_ENV = "DLROVER_TPU_GEN_SPEC"


def _import_factory(path: str) -> Callable:
    """"pkg.module:attr" -> callable."""
    mod_name, _, attr = path.partition(":")
    if not attr:
        raise ValueError(
            f"factory must be 'module:callable', got {path!r}"
        )
    import importlib

    return getattr(importlib.import_module(mod_name), attr)


def tiny_llama_factory(**cfg_kwargs):
    """Built-in factory: a llama sampler whose config comes from the
    spec (tests / example).  Returns the worker contract:
    ``forward_fn``, ``params_template_fn`` (inference-sharded params
    the shm snapshot restores ONTO)."""
    import jax

    from dlrover_tpu.models.llama import (
        LlamaConfig,
        forward,
        init_params,
    )

    cfg = LlamaConfig(**cfg_kwargs)

    def forward_fn(params, tokens):
        return forward(params, tokens, cfg)

    def params_template_fn():
        # the template's shardings ARE the inference layout; default:
        # replicated on this process's devices.  A multi-chip serving
        # mesh would device_put leaves onto its NamedShardings here.
        return init_params(jax.random.PRNGKey(0), cfg)

    return {
        "forward_fn": forward_fn,
        "params_template_fn": params_template_fn,
    }


def worker_main() -> int:
    """Generation-process entry (``python -m
    dlrover_tpu.rl.generation_service``); spec arrives via env."""
    import jax
    import jax.numpy as jnp

    from dlrover_tpu.agent.ckpt_shm import (
        SharedMemoryHandler,
        restore_to_target,
    )
    from dlrover_tpu.common.multi_process import SharedQueue
    from dlrover_tpu.rl.inference import JitSamplerBackend

    spec = json.loads(os.environ[WORKER_SPEC_ENV])
    name = spec["name"]
    factory = _import_factory(spec["factory"])
    parts = factory(**spec.get("factory_kwargs", {}))
    backend = JitSamplerBackend(
        parts["forward_fn"],
        max_new_tokens=int(spec["max_new_tokens"]),
        temperature=float(spec.get("temperature", 1.0)),
    )
    template = parts["params_template_fn"]()

    shm = SharedMemoryHandler(rank=0, name=name)
    req = SharedQueue(f"{name}-req", create=False)
    resp = SharedQueue(f"{name}-resp", create=False)
    version = -1
    handoff_s = 0.0
    resp.put({"ready": True, "pid": os.getpid()})
    logger.info("generation worker %s ready (pid %d)", name,
                os.getpid())
    while True:
        msg = req.get()
        cmd = msg.get("cmd")
        if cmd == "stop":
            resp.put({"stopped": True})
            return 0
        if cmd != "generate":
            resp.put({"error": f"unknown cmd {cmd!r}"})
            continue
        # a bad request (ragged prompts, shape-mismatched publish)
        # must answer {"error": ...}, not kill the worker — a dead
        # worker leaves every later client call blocking to timeout
        try:
            # weight refresh: adopt the newest published snapshot.
            # restore_to_target device_puts onto the TEMPLATE's
            # shardings — this is where the train layout reshards to
            # the inference layout (ref: ds_hybrid_engine's
            # train<->infer repartition)
            t0 = time.perf_counter()
            step, arrays = shm.load_state(copy=False)
            if step > version:
                template = restore_to_target(
                    template, arrays, to_device=True, copy_host=True
                )
                jax.block_until_ready(template)
                backend.sync_weights(template)
                version = step
                handoff_s = time.perf_counter() - t0
            del arrays
            prompts = jnp.asarray(msg["prompts"])
            rng = jax.random.PRNGKey(int(msg.get("seed", 0)))
            t1 = time.perf_counter()
            tokens = np.asarray(backend.generate(prompts, rng))
            gen_s = max(time.perf_counter() - t1, 1e-9)
            new_tokens = tokens.shape[1] - prompts.shape[1]
            resp.put(
                {
                    "tokens": tokens,
                    "version": version,
                    "handoff_s": round(handoff_s, 6),
                    "gen_s": round(gen_s, 6),
                    "tokens_per_s": round(
                        tokens.shape[0] * new_tokens / gen_s, 2
                    ),
                }
            )
        except Exception as e:  # noqa: BLE001 - per-request isolation
            logger.error("generation request failed: %s", e)
            resp.put({"error": f"{type(e).__name__}: {e}"})


class CrossProcessGenerationEngine:
    """Trainer-side handle on the generation process.

    Same surface as the in-process backends (``sync_weights`` /
    ``generate``) so PPO code swaps engines without edits; the
    difference is that ``sync_weights`` PUBLISHES the policy through
    shm (no pointer sharing) and ``generate`` is served by the worker
    process.  ``last_stats`` carries the serving metrics of the most
    recent call.
    """

    def __init__(
        self,
        factory: str,
        max_new_tokens: int,
        temperature: float = 1.0,
        factory_kwargs: Optional[Dict] = None,
        name: Optional[str] = None,
        start_timeout: float = 300.0,
    ):
        from dlrover_tpu.agent.ckpt_shm import SharedMemoryHandler
        from dlrover_tpu.common.multi_process import SharedQueue

        self._name = name or f"gen-{os.getpid()}"
        # trainer side hosts the meta service + queues (it outlives
        # worker restarts)
        self._shm = SharedMemoryHandler(
            rank=0, name=self._name, host=True
        )
        self._req = SharedQueue(f"{self._name}-req", create=True)
        self._resp = SharedQueue(f"{self._name}-resp", create=True)
        self._version = 0
        self.last_stats: Dict = {}
        self.publish_s = 0.0

        spec = {
            "name": self._name,
            "factory": factory,
            "factory_kwargs": factory_kwargs or {},
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature),
        }
        env = dict(os.environ)
        env[WORKER_SPEC_ENV] = json.dumps(spec)
        import jax

        if jax.default_backend() == "cpu":
            # tests / CPU: the worker must not grab a TPU runtime
            env.setdefault("JAX_PLATFORMS", "cpu")
        self._proc = subprocess.Popen(
            [sys.executable, "-m", "dlrover_tpu.rl.generation_service"],
            env=env,
        )
        ready = self._resp.get(timeout=start_timeout)
        if not ready.get("ready"):
            raise RuntimeError(f"generation worker failed: {ready}")
        logger.info(
            "cross-process generation engine %s up (worker pid %s)",
            self._name, ready.get("pid"),
        )

    # ------------------------------------------------------------ API
    def sync_weights(self, params) -> float:
        """Publish the actor params through the shm substrate; the
        worker adopts them before serving the next request.  Returns
        the publish (snapshot) latency in seconds."""
        self._version += 1
        t0 = time.perf_counter()
        self._shm.save_state(self._version, params)
        self.publish_s = time.perf_counter() - t0
        return self.publish_s

    def generate(self, prompts, rng=None, seed: Optional[int] = None):
        if seed is None:
            seed = 0
            if rng is not None:
                import jax

                seed = int(
                    np.asarray(jax.random.key_data(rng)).ravel()[-1]
                )
        self._req.put(
            {
                "cmd": "generate",
                "prompts": np.asarray(prompts),
                "seed": int(seed),
            }
        )
        out = self._get_response(timeout=600.0)
        if "error" in out:
            raise RuntimeError(out["error"])
        self.last_stats = {
            k: out[k]
            for k in ("version", "handoff_s", "gen_s", "tokens_per_s")
        }
        return out["tokens"]

    def _get_response(self, timeout: float, poll: float = 2.0) -> Dict:
        """Wait for the worker's response, watching the worker process:
        a dead worker must fail the call IMMEDIATELY with its exit
        code, not block the trainer for the full queue timeout
        (ADVICE-r5: generate() after a worker crash hung 600 s)."""
        import queue as _queue

        deadline = time.time() + timeout
        while True:
            try:
                return self._resp.get(
                    timeout=min(poll, max(deadline - time.time(), 0.1))
                )
            except _queue.Empty:
                rc = self._proc.poll()
                if rc is not None:
                    # the worker may have answered and THEN exited
                    # (queue flush is async): drain once more before
                    # declaring the request dead
                    try:
                        return self._resp.get(timeout=1.0)
                    except _queue.Empty:
                        pass
                    raise RuntimeError(
                        f"generation worker {self._name} died with "
                        f"exit code {rc} while serving a request"
                    ) from None
                if time.time() >= deadline:
                    raise TimeoutError(
                        f"generation worker {self._name} gave no "
                        f"response within {timeout}s"
                    ) from None

    def close(self):
        try:
            self._req.put({"cmd": "stop"})
            self._resp.get(timeout=30.0)
        except Exception:  # noqa: BLE001 - worker may be dead already
            pass
        if self._proc.poll() is None:
            try:
                self._proc.wait(timeout=30.0)
            except subprocess.TimeoutExpired:
                self._proc.kill()
        self._shm.close(unlink=True)
        self._req.close()
        self._resp.close()


if __name__ == "__main__":
    sys.exit(worker_main())
