"""Config-driven RLHF setup.

Reference parity: ``atorch/atorch/rl/config.py`` (YAML-driven PPO
config with per-role model/optimizer/strategy sections for actor /
critic / ref / reward).
"""

from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class RoleConfig:
    """One model role (actor / critic / ref_model / reward_model)."""

    train: bool = True
    learning_rate: float = 1e-6
    strategy: Dict = field(default_factory=dict)  # Strategy kwargs
    checkpoint_dir: str = ""


@dataclass
class PPOParams:
    gamma: float = 1.0
    lam: float = 0.95
    clip_ratio: float = 0.2
    value_clip: float = 0.2
    vf_coef: float = 0.5
    entropy_coef: float = 0.0
    kl_coef: float = 0.1  # penalty vs the frozen reference policy
    ppo_epochs: int = 1
    rollout_batch: int = 64


@dataclass
class RLConfig:
    roles: Dict[str, RoleConfig] = field(default_factory=dict)
    ppo: PPOParams = field(default_factory=PPOParams)
    max_prompt_len: int = 512
    max_response_len: int = 512

    @classmethod
    def from_dict(cls, raw: Dict) -> "RLConfig":
        roles = {
            name: RoleConfig(**cfg)
            for name, cfg in raw.get("roles", {}).items()
        }
        ppo = PPOParams(**raw.get("ppo", {}))
        return cls(
            roles=roles,
            ppo=ppo,
            max_prompt_len=raw.get("max_prompt_len", 512),
            max_response_len=raw.get("max_response_len", 512),
        )

    def role(self, name: str) -> Optional[RoleConfig]:
        return self.roles.get(name)
