from dlrover_tpu.rl.config import RLConfig, RoleConfig  # noqa: F401
from dlrover_tpu.rl.engine import ModelEngine  # noqa: F401
from dlrover_tpu.rl.ppo import (  # noqa: F401
    compute_gae,
    ppo_loss,
    ReplayBuffer,
)
