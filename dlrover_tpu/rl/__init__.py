from dlrover_tpu.rl.config import RLConfig, RoleConfig  # noqa: F401
from dlrover_tpu.rl.engine import ModelEngine  # noqa: F401
from dlrover_tpu.rl.ppo import (  # noqa: F401
    compute_gae,
    ppo_loss,
    ReplayBuffer,
)
from dlrover_tpu.rl.inference import (  # noqa: F401
    InferenceBackend,
    JitSamplerBackend,
    KVCacheBackend,
)
from dlrover_tpu.rl.trainer import RLHFTrainer  # noqa: F401
from dlrover_tpu.rl.kv_cache import (  # noqa: F401
    BlockPool,
    PagedCacheConfig,
    init_block_pool,
)
from dlrover_tpu.rl.scheduler import (  # noqa: F401
    ContinuousBatchingScheduler,
    GenRequest,
    GenResult,
    SchedulerConfig,
)
