"""Inference backends for RLHF generation.

Reference parity: ``atorch/atorch/rl/inference_backend/
vllm_backend.py`` — the actor's rollout generation runs on a dedicated
serving engine whose weights are synced from the trainer.  The TPU
duals:

- :class:`JitSamplerBackend` — full-forward autoregressive sampling
  (no cache); simple, correct, O(T^2) — fine for short responses.
- :class:`KVCacheBackend` — prefill + cached decode via the model's
  ``decode_step`` (the vLLM-style serving path): a T-token generation
  costs one prefill plus T O(1)-attention steps on the training mesh.
  The prefill is a single batched forward that fills the whole cache
  in one call when the model provides one (``models.llama.prefill``);
  models without a prefill fn fall back to feeding the prompt one
  token at a time through ``lax.scan``
  (``DLROVER_TPU_GEN_BATCHED_PREFILL=0`` forces the scan path).

Both expose ``generate(params, prompts, rng)`` and take their weights
directly from the live train state (``sync_weights`` is a pointer
swap — trainer and generator share the mesh, so there is no
cross-process weight shipping like the reference needs for vLLM).

Shape bucketing (``DLROVER_TPU_GEN_BUCKETS``, e.g. ``"16,32,64"``):
both backends jit-compile per input shape, so a stream of
distinct-length prompts used to retrace per ``[B, P]``.  With buckets
set, prompts pad up to the smallest bucket >= their length and the
REAL length rides in as a traced scalar — one compile per (batch,
bucket), and causal masking makes the padded result identical to the
exact-shape one at ANY temperature (padding sits strictly to the
right of every attended position, and the batch dim — which shapes
the sampler's noise — is never padded).  The continuous-batching
scheduler (``rl/scheduler.py``) goes further — fixed slot lanes, zero
retraces — this keeps the whole-batch backends cheap for RLHF
rollouts.
"""

from abc import ABCMeta, abstractmethod
from functools import partial
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from dlrover_tpu.common.env import (
    gen_batched_prefill_enabled,
    gen_buckets,
)


def bucket_len(plen: int, buckets: Tuple[int, ...]) -> int:
    """The padded length for a ``plen``-token prompt: the smallest
    bucket that fits, exact length when none does (an oversized
    prompt must still run).  ONLY the length dim buckets — padding
    the batch dim would change what ``jax.random.categorical`` draws
    per row (its noise is shaped by the full batch), breaking the
    identical-results contract at temperature > 0."""
    for bk in buckets:
        if bk >= plen:
            return bk
    return plen


def _pad_prompts(prompts, padded_len: int):
    plen = prompts.shape[1]
    return jnp.pad(prompts, ((0, 0), (0, padded_len - plen)))


class InferenceBackend(metaclass=ABCMeta):
    """Generation engine fed from the trainer's weights."""

    def __init__(self):
        self._params = None

    def sync_weights(self, params):
        """Point the backend at the trainer's current actor params (a
        reference swap — same device memory, no copy)."""
        self._params = params

    @abstractmethod
    def generate(self, prompts, rng, params=None):
        """prompts [B, P] -> tokens [B, P + max_new] (left part
        verbatim, right part sampled)."""

    def compile_count(self) -> int:
        """How many programs the backend's jitted generator holds —
        the bucket satellite's regression meter (one per bucket, not
        one per distinct ``[B, P]``)."""
        fn = getattr(self, "_compiled_fn", None)
        try:
            return int(fn._cache_size())
        except Exception:  # noqa: BLE001 - jax-version specific
            return -1


class JitSamplerBackend(InferenceBackend):
    """Full-forward sampler (no KV cache)."""

    def __init__(self, forward_fn: Callable, max_new_tokens: int,
                 temperature: float = 1.0):
        super().__init__()
        from dlrover_tpu.rl.engine import ModelEngine

        self._max_new = int(max_new_tokens)
        self._sample = ModelEngine.make_sampler(
            forward_fn, max_new_tokens, temperature
        )
        self._compiled_fn = self._sample

    def generate(self, prompts, rng, params=None):
        params = params if params is not None else self._params
        prompts = jnp.asarray(prompts)
        plen = prompts.shape[1]
        buckets = gen_buckets()
        if not buckets:
            return self._sample(params, prompts, rng)
        out = self._sample(
            params,
            _pad_prompts(prompts, bucket_len(plen, buckets)),
            rng,
            jnp.int32(plen),
        )
        return out[:, : plen + self._max_new]


class KVCacheBackend(InferenceBackend):
    """Prefill + cached decode on the model's ``decode_step``.

    ``cfg`` is the model's LlamaConfig (or any config accepted by the
    supplied ``decode_step_fn``/``init_cache_fn``).  ``prefill_fn``
    (``(params, tokens, cache) -> (logits [B, P, V], cache)``)
    enables the batched single-forward prefill; the default wires the
    llama one when the default decode fns are in use, and models
    without one keep the scan path."""

    _AUTO = object()

    def __init__(
        self,
        cfg,
        max_new_tokens: int,
        temperature: float = 1.0,
        decode_step_fn: Optional[Callable] = None,
        init_cache_fn: Optional[Callable] = None,
        prefill_fn=_AUTO,
    ):
        super().__init__()
        from dlrover_tpu.models import llama

        self._cfg = cfg
        self._max_new = int(max_new_tokens)
        self._temp = temperature
        default_model = decode_step_fn is None and init_cache_fn is None
        self._decode = decode_step_fn or partial(
            llama.decode_step, cfg=cfg
        )
        self._init_cache = init_cache_fn or partial(
            llama.init_kv_cache, cfg
        )
        if prefill_fn is KVCacheBackend._AUTO:
            prefill_fn = (
                partial(llama.prefill, cfg=cfg)
                if default_model
                else None
            )
        if not gen_batched_prefill_enabled():
            prefill_fn = None
        self._prefill = prefill_fn
        self._generate = jax.jit(self._build())
        self._compiled_fn = self._generate

    def _build(self):
        decode, temp, max_new = self._decode, self._temp, self._max_new
        init_cache, cfg = self._init_cache, self._cfg
        batched_prefill = self._prefill

        def generate(params, prompts, plen, rng):
            b, padded_len = prompts.shape
            total = padded_len + max_new
            cache = init_cache(b, total)

            if batched_prefill is not None:
                # one forward fills every prompt position's K/V; the
                # last REAL position's logits seed the first sample
                all_logits, cache = batched_prefill(
                    params, prompts, cache
                )
                logits = jnp.take(all_logits, plen - 1, axis=1)
            else:
                # scan fallback: feed the prompt one position at a
                # time through the cached step, carrying the logits
                # of the last real position (padding runs past it)
                def prefill_step(carry, t):
                    cache, last = carry
                    logits, cache = decode(
                        params, prompts[:, t], cache, t
                    )
                    last = jnp.where(t == plen - 1, logits, last)
                    return (cache, last), None

                (cache, logits), _ = jax.lax.scan(
                    prefill_step,
                    (
                        cache,
                        jnp.zeros(
                            (b, cfg.vocab_size), jnp.float32
                        ),
                    ),
                    jnp.arange(padded_len),
                )

            out = jnp.concatenate(
                [
                    prompts,
                    jnp.zeros((b, max_new), dtype=prompts.dtype),
                ],
                axis=1,
            )

            def step(carry, i):
                out, cache, logits, rng = carry
                pos = plen + i
                rng, sub = jax.random.split(rng)
                if temp <= 0:
                    nxt = jnp.argmax(logits, axis=-1)
                else:
                    nxt = jax.random.categorical(
                        sub, logits / temp, axis=-1
                    )
                nxt = nxt.astype(out.dtype)
                out = jax.lax.dynamic_update_slice(
                    out, nxt[:, None], (0, pos)
                )
                logits, cache = decode(params, nxt, cache, pos)
                return (out, cache, logits, rng), None

            (out, cache, logits, rng), _ = jax.lax.scan(
                step, (out, cache, logits, rng),
                jnp.arange(max_new),
            )
            return out

        return generate

    def generate(self, prompts, rng, params=None):
        params = params if params is not None else self._params
        prompts = jnp.asarray(prompts)
        plen = prompts.shape[1]
        buckets = gen_buckets()
        if not buckets:
            return self._generate(
                params, prompts, jnp.int32(plen), rng
            )
        out = self._generate(
            params,
            _pad_prompts(prompts, bucket_len(plen, buckets)),
            jnp.int32(plen),
            rng,
        )
        return out[:, : plen + self._max_new]
