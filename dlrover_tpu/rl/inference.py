"""Inference backends for RLHF generation.

Reference parity: ``atorch/atorch/rl/inference_backend/
vllm_backend.py`` — the actor's rollout generation runs on a dedicated
serving engine whose weights are synced from the trainer.  The TPU
duals:

- :class:`JitSamplerBackend` — full-forward autoregressive sampling
  (no cache); simple, correct, O(T^2) — fine for short responses.
- :class:`KVCacheBackend` — prefill + cached decode via the model's
  ``decode_step`` (the vLLM-style serving path): a T-token generation
  costs one prefill plus T O(1)-attention steps on the training mesh.

Both expose ``generate(params, prompts, rng)`` and take their weights
directly from the live train state (``sync_weights`` is a pointer
swap — trainer and generator share the mesh, so there is no
cross-process weight shipping like the reference needs for vLLM).
"""

from abc import ABCMeta, abstractmethod
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp


class InferenceBackend(metaclass=ABCMeta):
    """Generation engine fed from the trainer's weights."""

    def __init__(self):
        self._params = None

    def sync_weights(self, params):
        """Point the backend at the trainer's current actor params (a
        reference swap — same device memory, no copy)."""
        self._params = params

    @abstractmethod
    def generate(self, prompts, rng, params=None):
        """prompts [B, P] -> tokens [B, P + max_new] (left part
        verbatim, right part sampled)."""


class JitSamplerBackend(InferenceBackend):
    """Full-forward sampler (no KV cache)."""

    def __init__(self, forward_fn: Callable, max_new_tokens: int,
                 temperature: float = 1.0):
        super().__init__()
        from dlrover_tpu.rl.engine import ModelEngine

        self._sample = ModelEngine.make_sampler(
            forward_fn, max_new_tokens, temperature
        )

    def generate(self, prompts, rng, params=None):
        return self._sample(
            params if params is not None else self._params,
            prompts, rng,
        )


class KVCacheBackend(InferenceBackend):
    """Prefill + cached decode on the model's ``decode_step``.

    ``cfg`` is the model's LlamaConfig (or any config accepted by the
    supplied ``decode_step_fn``/``init_cache_fn``)."""

    def __init__(
        self,
        cfg,
        max_new_tokens: int,
        temperature: float = 1.0,
        decode_step_fn: Optional[Callable] = None,
        init_cache_fn: Optional[Callable] = None,
    ):
        super().__init__()
        from dlrover_tpu.models import llama

        self._cfg = cfg
        self._max_new = max_new_tokens
        self._temp = temperature
        self._decode = decode_step_fn or partial(
            llama.decode_step, cfg=cfg
        )
        self._init_cache = init_cache_fn or partial(
            llama.init_kv_cache, cfg
        )
        self._generate = jax.jit(self._build())

    def _build(self):
        decode, temp, max_new = self._decode, self._temp, self._max_new
        init_cache, cfg = self._init_cache, self._cfg

        def generate(params, prompts, rng):
            b, plen = prompts.shape
            total = plen + max_new
            cache = init_cache(b, total)

            # prefill: feed prompt tokens one position at a time
            # through the cached step (keeps ONE compiled program; a
            # batched prefill kernel can swap in without API change)
            def prefill(carry, t):
                cache, _last = carry
                logits, cache = decode(params, prompts[:, t], cache, t)
                return (cache, logits), None

            (cache, logits), _ = jax.lax.scan(
                prefill,
                (cache, jnp.zeros((b, cfg.vocab_size), jnp.float32)),
                jnp.arange(plen),
            )

            out = jnp.zeros((b, total), dtype=prompts.dtype)
            out = out.at[:, :plen].set(prompts)

            def step(carry, t):
                out, cache, logits, rng = carry
                rng, sub = jax.random.split(rng)
                if temp <= 0:
                    nxt = jnp.argmax(logits, axis=-1)
                else:
                    nxt = jax.random.categorical(
                        sub, logits / temp, axis=-1
                    )
                nxt = nxt.astype(out.dtype)
                out = jax.lax.dynamic_update_slice(
                    out, nxt[:, None], (0, t)
                )
                logits, cache = decode(params, nxt, cache, t)
                return (out, cache, logits, rng), None

            (out, cache, logits, rng), _ = jax.lax.scan(
                step, (out, cache, logits, rng),
                jnp.arange(plen, total),
            )
            return out

        return generate

    def generate(self, prompts, rng, params=None):
        return self._generate(
            params if params is not None else self._params,
            prompts, rng,
        )
