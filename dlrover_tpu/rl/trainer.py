"""RLHF PPO orchestration over the per-role engine.

Reference parity: ``atorch/atorch/rl/main.py`` + the model engine /
trainer split (``rl/model_engine/model_engine.py``) — four roles
(actor / critic / ref / reward), rollout generation on an inference
backend with weights synced from the trainer, experience-making with
KL-shaped rewards and GAE, then clipped-PPO updates of actor and
critic through their own accelerated train steps.

Model-agnostic: the caller supplies ``actor_forward(params, tokens) ->
logits`` and ``critic_value(params, tokens) -> values [B, S]``; roles
are built through :class:`dlrover_tpu.rl.engine.ModelEngine`, so each
role gets its own parallelization strategy.
"""

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.rl.config import RLConfig
from dlrover_tpu.rl.engine import ModelEngine
from dlrover_tpu.rl.inference import InferenceBackend
from dlrover_tpu.rl.ppo import ReplayBuffer, compute_gae


def token_logprobs(logits: jnp.ndarray, tokens: jnp.ndarray):
    """Next-token logprob per position: [B, S, V], [B, S] -> [B, S-1]."""
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), -1)
    return jnp.take_along_axis(
        logp, tokens[:, 1:, None], axis=-1
    )[..., 0]


def actor_ppo_loss(
    logits, batch, clip_ratio: float = 0.2, kl_coef: float = 0.1
):
    """Clipped surrogate + KL penalty (the policy half of
    ``ppo.ppo_loss``; the value half lives in the critic's loss)."""
    mask = batch["mask"]
    msum = jnp.maximum(jnp.sum(mask), 1.0)
    logp = token_logprobs(logits, batch["tokens"])
    adv = batch["advantages"]
    amean = jnp.sum(adv * mask) / msum
    astd = jnp.sqrt(
        jnp.sum(((adv - amean) ** 2) * mask) / msum + 1e-8
    )
    adv = (adv - amean) / astd
    ratio = jnp.exp(logp - batch["old_logp"])
    unclipped = ratio * adv
    clipped = jnp.clip(ratio, 1 - clip_ratio, 1 + clip_ratio) * adv
    policy_loss = -jnp.sum(jnp.minimum(unclipped, clipped) * mask) / msum
    kl = jnp.sum((logp - batch["ref_logp"]) * mask) / msum
    return policy_loss + kl_coef * kl


def critic_value_loss(values, batch, value_clip: float = 0.2):
    """Clipped value regression (the value half of ``ppo.ppo_loss``)."""
    mask = batch["mask"]
    msum = jnp.maximum(jnp.sum(mask), 1.0)
    v = values[:, :-1]
    old_v = batch["old_values"]
    v_clip = old_v + jnp.clip(v - old_v, -value_clip, value_clip)
    returns = batch["returns"]
    return 0.5 * jnp.sum(
        jnp.maximum((v - returns) ** 2, (v_clip - returns) ** 2) * mask
    ) / msum


class RLHFTrainer:
    """Rollout -> experience -> PPO epochs, over engine-built roles."""

    def __init__(
        self,
        config: RLConfig,
        engine: ModelEngine,
        backend: InferenceBackend,
        actor_forward: Callable,
        critic_value: Callable,
        reward_fn: Callable,  # (tokens [B, S]) -> [B] sequence reward
        prompt_len: int,
    ):
        self.config = config
        self.engine = engine
        self.backend = backend
        self._actor_forward = actor_forward
        self._critic_value = critic_value
        self._reward_fn = reward_fn
        self._prompt_len = prompt_len
        self.buffer = ReplayBuffer()
        # frozen reference policy = actor params at construction
        self._ref_params = jax.tree_util.tree_map(
            jnp.copy, engine.states["actor"]["params"]
        )
        self._logp_fn = jax.jit(
            lambda p, t: token_logprobs(actor_forward(p, t), t)
        )
        self._value_fn = jax.jit(critic_value)
        ppo = config.ppo
        # batched GAE: one dispatch for the whole rollout instead of a
        # per-sample trace/transfer loop
        self._gae_fn = jax.jit(
            jax.vmap(
                lambda r, v: compute_gae(
                    r, v, gamma=ppo.gamma, lam=ppo.lam
                )
            )
        )

    # -- experience ------------------------------------------------------
    def make_experience(self, prompts: jnp.ndarray, rng) -> Dict:
        """Generate responses, score them, compute advantages, fill the
        replay buffer; returns rollout stats."""
        ppo = self.config.ppo
        actor_params = self.engine.states["actor"]["params"]
        self.backend.sync_weights(actor_params)
        tokens = np.asarray(self.backend.generate(prompts, rng))

        mask = np.zeros(tokens.shape[:2], np.float32)
        mask[:, self._prompt_len :] = 1.0
        mask_t = mask[:, 1:]  # aligned with next-token logprobs

        old_logp = np.asarray(self._logp_fn(actor_params, tokens))
        ref_logp = np.asarray(self._logp_fn(self._ref_params, tokens))
        values = np.asarray(
            self._value_fn(
                self.engine.states["critic"]["params"], tokens
            )
        )
        seq_reward = np.asarray(self._reward_fn(tokens))

        b, total = tokens.shape
        # KL-shaped per-token rewards, sequence reward at the last
        # response token — vectorized over the rollout
        r = -ppo.kl_coef * (old_logp - ref_logp) * mask_t
        has_resp = mask_t.any(axis=1)
        last = np.where(
            has_resp,
            (mask_t * np.arange(total - 1)[None]).argmax(axis=1),
            total - 2,
        )
        r[np.arange(b), last] += seq_reward
        adv, ret = self._gae_fn(jnp.asarray(r), jnp.asarray(values))
        adv, ret = np.asarray(adv), np.asarray(ret)
        for i in range(b):
            self.buffer.add(
                {
                    "tokens": tokens[i],
                    "mask": mask_t[i],
                    "old_logp": old_logp[i],
                    "ref_logp": ref_logp[i],
                    "old_values": values[i, :-1],
                    "advantages": adv[i],
                    "returns": ret[i],
                }
            )
        return {
            "mean_reward": float(seq_reward.mean()),
            "mean_kl": float(
                ((old_logp - ref_logp) * mask_t).sum()
                / max(mask_t.sum(), 1.0)
            ),
        }

    def experience_from_trajectories(self, trajectories) -> Dict:
        """Flywheel intake (ISSUE 20): build PPO experience straight
        from streamed :class:`dlrover_tpu.rl.flywheel.Trajectory`
        samples, using each trajectory's CAPTURED per-token logprobs
        as ``old_logp`` — the actor recompute forward of
        :meth:`make_experience` disappears (the reference and value
        forwards remain; the frozen ref policy never sampled and the
        critic never saw the rollout).  Captured logprobs are
        ``log_softmax`` of the sampling policy's raw fp32 logits —
        exactly what ``token_logprobs`` would recompute — so the two
        paths are numerically identical; NaN entries (positions a
        resume hop could not carry) fall back to one recompute pass
        for the whole batch."""
        ppo = self.config.ppo
        if not trajectories:
            return {"mean_reward": 0.0, "mean_kl": 0.0, "samples": 0}
        b = len(trajectories)
        total = max(int(t.tokens.size) for t in trajectories)
        tokens = np.zeros((b, total), np.int32)
        mask_t = np.zeros((b, total - 1), np.float32)
        old_logp = np.zeros((b, total - 1), np.float32)
        for i, t in enumerate(trajectories):
            n = int(t.tokens.size)
            tokens[i, :n] = t.tokens
            lo = int(t.prompt_len)
            hi = lo + int(t.new_tokens)
            # the response token at position p pairs with next-token
            # logprob row p-1
            mask_t[i, lo - 1:hi - 1] = 1.0
            lp = np.asarray(t.logprobs, np.float32).reshape(-1)
            row = np.full((hi - lo,), np.nan, np.float32)
            row[: min(lp.size, hi - lo)] = lp[: hi - lo]
            old_logp[i, lo - 1:hi - 1] = row
        actor_params = self.engine.states["actor"]["params"]
        if np.isnan(old_logp[mask_t > 0]).any():
            recomputed = np.asarray(
                self._logp_fn(actor_params, tokens)
            )
            old_logp = np.where(
                np.isnan(old_logp), recomputed, old_logp
            )
        else:
            old_logp = np.nan_to_num(old_logp)
        ref_logp = np.asarray(self._logp_fn(self._ref_params, tokens))
        values = np.asarray(
            self._value_fn(
                self.engine.states["critic"]["params"], tokens
            )
        )
        seq_reward = np.asarray(self._reward_fn(tokens))
        r = -ppo.kl_coef * (old_logp - ref_logp) * mask_t
        has_resp = mask_t.any(axis=1)
        last = np.where(
            has_resp,
            (mask_t * np.arange(total - 1)[None]).argmax(axis=1),
            total - 2,
        )
        r[np.arange(b), last] += seq_reward
        adv, ret = self._gae_fn(jnp.asarray(r), jnp.asarray(values))
        adv, ret = np.asarray(adv), np.asarray(ret)
        for i in range(b):
            self.buffer.add(
                {
                    "tokens": tokens[i],
                    "mask": mask_t[i],
                    "old_logp": old_logp[i],
                    "ref_logp": ref_logp[i],
                    "old_values": values[i, :-1],
                    "advantages": adv[i],
                    "returns": ret[i],
                }
            )
        return {
            "mean_reward": float(seq_reward.mean()),
            "mean_kl": float(
                ((old_logp - ref_logp) * mask_t).sum()
                / max(mask_t.sum(), 1.0)
            ),
            "samples": b,
        }

    # -- optimization ----------------------------------------------------
    def train_on_buffer(self, batch_size: int) -> Dict:
        """PPO epochs over the buffered experience through each role's
        accelerated train step."""
        stats = {"actor_loss": [], "critic_loss": []}
        actor = self.engine.roles["actor"].fns
        critic = self.engine.roles["critic"].fns
        for batch in self.buffer.sample_batches(
            batch_size, epochs=self.config.ppo.ppo_epochs
        ):
            batch = {k: jnp.asarray(v) for k, v in batch.items()}
            self.engine.states["actor"], m_a = actor.train_step(
                self.engine.states["actor"],
                jax.device_put(batch, actor.batch_sharding),
            )
            stats["actor_loss"].append(float(m_a["loss"]))
            self.engine.states["critic"], m_c = critic.train_step(
                self.engine.states["critic"],
                jax.device_put(batch, critic.batch_sharding),
            )
            stats["critic_loss"].append(float(m_c["loss"]))
        self.buffer.clear()
        return {
            k: float(np.mean(v)) if v else 0.0
            for k, v in stats.items()
        }

    def train(
        self,
        prompt_batches,
        rng,
        minibatch_size: Optional[int] = None,
    ):
        """The outer PPO loop (reference ``rl/main.py``)."""
        ppo = self.config.ppo
        minibatch_size = minibatch_size or ppo.rollout_batch
        history = []
        for step, prompts in enumerate(prompt_batches):
            rng, sub = jax.random.split(rng)
            roll = self.make_experience(jnp.asarray(prompts), sub)
            opt = self.train_on_buffer(minibatch_size)
            logger.info(
                "rlhf step %d: reward %.4f kl %.4f actor %.4f "
                "critic %.4f",
                step, roll["mean_reward"], roll["mean_kl"],
                opt["actor_loss"], opt["critic_loss"],
            )
            history.append({**roll, **opt})
        return history
