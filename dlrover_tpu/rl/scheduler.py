"""Token-level (continuous-batching) generation scheduler.

Reference parity: Orca's iteration-level scheduling + vLLM's block
tables — the serving loop the reference system gets from its vLLM
backend.  The repo's request/queue loop (``generation_service``'s
single worker) serves one whole batch to completion before admitting
the next request; here scheduling happens at TOKEN granularity:

- the batch is ``max_slots`` fixed LANES, each holding (or not) one
  live sequence — an active-mask, never a shape change;
- ONE jitted decode program (``models.llama.paged_decode_step`` over
  the ``rl/kv_cache`` block pool) advances every active lane by one
  token per iteration; admissions and evictions mutate host-side
  arrays (block tables, positions, masks) only, so the program
  compiles exactly once and never retraces across arbitrary traffic;
- prompts prefill in fixed-size CHUNKS (one chunk per iteration,
  round-robin) interleaved with running decodes — a 10k-token prompt
  costs the running sequences a bounded slice per iteration instead
  of stalling them for its whole prefill;
- a sequence leaves its slot the moment it hits EOS or its token
  budget, and the freed slot admits the next queued prompt on the
  SAME iteration — mixed-length traffic never waits for the longest
  sequence in a batch (the dense-batch pathology this replaces).

Determinism: each request's tokens are sampled with
``fold_in(PRNGKey(seed), position)`` — a function of (seed, position)
only, independent of which slot/iteration served it.  The same
request produces the same tokens whether it ran alone, continuously
batched, after a drain-requeue, or on a different replica; tests pin
tail parity against an unbatched reference on exactly this property.
"""

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.rl.kv_cache import (
    BlockPool,
    PagedCacheConfig,
    init_block_pool,
)

FINISH_EOS = "eos"
FINISH_LENGTH = "length"


@dataclass
class GenRequest:
    """One generation request (prompt in, sampled tail out)."""

    req_id: int
    prompt: np.ndarray  # [P] int32
    max_new: int
    seed: int = 0
    submit_t: float = field(default_factory=time.monotonic)


@dataclass
class GenResult:
    req_id: int
    tokens: np.ndarray  # [P + new] int32 (prompt verbatim + tail)
    finish_reason: str
    new_tokens: int
    latency_s: float
    stats: Dict = field(default_factory=dict)


@dataclass(frozen=True)
class SchedulerConfig:
    """Serving geometry: every field is a STATIC shape input of the
    compiled programs — change one and you get (exactly) one new
    compile, change traffic and you get none."""

    max_slots: int = 8  # decode lanes
    block_size: int = 16  # tokens per KV block
    num_blocks: int = 256  # pool size incl. the null block
    max_seq_len: int = 512  # longest prompt+tail a slot may hold
    prefill_chunk: int = 32  # prompt tokens prefilled per iteration
    max_new_default: int = 64
    temperature: float = 1.0
    eos_id: Optional[int] = None

    @property
    def max_blocks_per_seq(self) -> int:
        return -(-self.max_seq_len // self.block_size)


@dataclass
class _Slot:
    req: Optional[GenRequest] = None
    phase: str = "free"  # free | prefill | decode
    prefill_pos: int = 0
    generated: List[int] = field(default_factory=list)
    first_token_t: float = 0.0


class ContinuousBatchingScheduler:
    """The token-level serving loop over a paged KV cache.

    ``model_cfg`` is a ``models.llama.LlamaConfig`` (or any config the
    supplied ``paged_decode_fn`` / ``paged_prefill_fn`` accept — the
    same injection seam ``KVCacheBackend`` uses)."""

    def __init__(
        self,
        model_cfg,
        sched: Optional[SchedulerConfig] = None,
        paged_decode_fn: Optional[Callable] = None,
        paged_prefill_fn: Optional[Callable] = None,
        events=None,
    ):
        import jax
        import jax.numpy as jnp
        from functools import partial

        from dlrover_tpu.models import llama

        self._jax, self._jnp = jax, jnp
        self.cfg = model_cfg
        self.sched = sched or SchedulerConfig()
        s = self.sched
        if s.prefill_chunk < 1 or s.max_slots < 1:
            raise ValueError("prefill_chunk and max_slots must be >= 1")
        self._events = events
        self._params = None
        self._decode_model = paged_decode_fn or partial(
            llama.paged_decode_step, cfg=model_cfg
        )
        self._prefill_model = paged_prefill_fn or partial(
            llama.paged_prefill_chunk, cfg=model_cfg
        )

        cache_cfg = PagedCacheConfig(
            n_layers=model_cfg.n_layers,
            n_kv_heads=model_cfg.n_kv_heads,
            head_dim=model_cfg.head_dim,
            num_blocks=s.num_blocks,
            block_size=s.block_size,
            dtype=model_cfg.dtype,
        )
        self.pool_cfg = cache_cfg
        self.block_pool = BlockPool(cache_cfg)
        self._pool = init_block_pool(cache_cfg)

        # host mirrors of the fixed-shape device inputs
        S, MB = s.max_slots, s.max_blocks_per_seq
        self._tables = np.zeros((S, MB), np.int32)
        self._positions = np.zeros((S,), np.int32)
        self._active = np.zeros((S,), bool)
        self._next_token = np.zeros((S,), np.int32)
        self._keys = np.zeros((S, 2), np.uint32)
        self._slots = [_Slot() for _ in range(S)]
        self._queue: List[GenRequest] = []
        self._next_req_id = 0
        self._prefill_rr = 0  # round-robin pointer over prefill slots
        self.draining = False

        # counters the serving gauges/bench read
        self.total_new_tokens = 0
        self.total_prefill_tokens = 0
        self.iterations = 0

        temp = float(s.temperature)

        def _sample_rows(logits, keys, sample_pos):
            """logits [S, V]; keys [S, 2] request base keys;
            sample_pos [S] the OUTPUT position each token will occupy
            — the (seed, position)-only sampling contract."""
            if temp <= 0:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            folded = jax.vmap(jax.random.fold_in)(keys, sample_pos)
            return jax.vmap(
                lambda k, l: jax.random.categorical(k, l / temp)
            )(folded, logits).astype(jnp.int32)

        def _decode(params, pool, tokens, tables, positions, active,
                    keys):
            logits, pool = self._decode_model(
                params, tokens, pool, tables, positions, active
            )
            nxt = _sample_rows(logits, keys, positions + 1)
            return pool, nxt

        def _prefill(params, pool, chunk, table, start):
            logits, pool = self._prefill_model(
                params, chunk, pool, table, start
            )
            return pool, logits

        def _sample_one(logits_row, key, sample_pos):
            return _sample_rows(
                logits_row[None], key[None], sample_pos[None]
            )[0]

        self._decode_jit = jax.jit(_decode, donate_argnums=(1,))
        self._prefill_jit = jax.jit(_prefill, donate_argnums=(1,))
        self._sample_jit = jax.jit(_sample_one)

    # ------------------------------------------------------------- API
    def sync_weights(self, params):
        """Adopt the trainer's / publisher's current params (reference
        swap; in-flight sequences continue on the new weights — the
        vLLM-backend weight-refresh semantics)."""
        self._params = params

    def submit(
        self,
        prompt,
        max_new: Optional[int] = None,
        seed: int = 0,
        req_id: Optional[int] = None,
    ) -> int:
        """Queue one prompt; returns the request id results carry."""
        if self.draining:
            raise RuntimeError(
                "scheduler is draining: submissions belong on "
                "another replica (the dispatcher requeues them)"
            )
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            # position-0 sampling would condition on pool garbage —
            # there is no (seed, position)-pure answer for it
            raise ValueError("prompt must hold at least one token")
        max_new = int(
            self.sched.max_new_default if max_new is None else max_new
        )
        if max_new < 1:
            raise ValueError("max_new must be >= 1")
        if prompt.size + max_new > self.sched.max_seq_len:
            raise ValueError(
                f"prompt {prompt.size} + max_new {max_new} exceeds "
                f"max_seq_len {self.sched.max_seq_len}"
            )
        if req_id is None:
            req_id = self._next_req_id
        self._next_req_id = max(self._next_req_id, req_id) + 1
        self._queue.append(
            GenRequest(req_id=req_id, prompt=prompt, max_new=max_new,
                       seed=int(seed))
        )
        return req_id

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def active_count(self) -> int:
        return sum(1 for sl in self._slots if sl.req is not None)

    @property
    def idle(self) -> bool:
        return not self._queue and self.active_count == 0

    def compile_counts(self) -> Dict[str, int]:
        """Compiled-program census: decode must stay at 1 across any
        admission/eviction traffic (asserted by tier-1)."""

        def n(f):
            try:
                return int(f._cache_size())
            except Exception:  # noqa: BLE001 - jax-version specific
                return -1

        return {
            "decode": n(self._decode_jit),
            "prefill": n(self._prefill_jit),
            "sample": n(self._sample_jit),
        }

    def stats(self) -> Dict:
        st = dict(self.block_pool.stats())
        st.update(
            queue_depth=self.queue_depth,
            active=self.active_count,
            iterations=self.iterations,
            total_new_tokens=self.total_new_tokens,
            total_prefill_tokens=self.total_prefill_tokens,
        )
        return st

    # ------------------------------------------------------ scheduling
    def _admit(self):
        s = self.sched
        while self._queue and not self.draining:
            free = [
                i for i, sl in enumerate(self._slots)
                if sl.req is None
            ]
            if not free:
                return
            req = self._queue[0]
            need = req.prompt.size + req.max_new
            if not self.block_pool.can_allocate(need):
                # FIFO head-of-line: later (smaller) requests must not
                # starve the head forever
                return
            self._queue.pop(0)
            slot = free[0]
            self.block_pool.allocate(req.req_id, need)
            row = self.block_pool.table_row(
                req.req_id, s.max_blocks_per_seq
            )
            self._tables[slot] = row
            self._positions[slot] = 0
            self._active[slot] = False  # decoding starts post-prefill
            key = self._jax.random.PRNGKey(req.seed)
            self._keys[slot] = np.asarray(
                self._jax.random.key_data(key), np.uint32
            ).reshape(-1)[:2]
            self._slots[slot] = _Slot(req=req, phase="prefill")

    def _finish(self, slot: int, reason: str,
                finished: List[GenResult]):
        sl = self._slots[slot]
        req = sl.req
        now = time.monotonic()
        tokens = np.concatenate(
            [req.prompt, np.asarray(sl.generated, np.int32)]
        )
        finished.append(
            GenResult(
                req_id=req.req_id,
                tokens=tokens,
                finish_reason=reason,
                new_tokens=len(sl.generated),
                latency_s=now - req.submit_t,
                stats={
                    "ttft_s": round(
                        max(sl.first_token_t - req.submit_t, 0.0), 6
                    ),
                },
            )
        )
        self.block_pool.free(req.req_id)
        # zero the table row: a freed block re-issued to another
        # sequence must never be gathered through this lane again
        self._tables[slot] = 0
        self._positions[slot] = 0
        self._active[slot] = False
        self._slots[slot] = _Slot()

    def _append_token(self, slot: int, token: int,
                      finished: List[GenResult]) -> bool:
        """Append one sampled token; returns True when the sequence
        finished (EOS / budget) and left its slot."""
        sl = self._slots[slot]
        if not sl.generated:
            sl.first_token_t = time.monotonic()
        sl.generated.append(int(token))
        self.total_new_tokens += 1
        eos = self.sched.eos_id
        if eos is not None and int(token) == int(eos):
            self._finish(slot, FINISH_EOS, finished)
            return True
        if len(sl.generated) >= sl.req.max_new:
            self._finish(slot, FINISH_LENGTH, finished)
            return True
        return False

    def _prefill_one(self, finished: List[GenResult]) -> int:
        """Run ONE prompt chunk (round-robin over prefilling slots);
        returns the number of prompt tokens processed."""
        s = self.sched
        slots = [
            i for i, sl in enumerate(self._slots)
            if sl.phase == "prefill"
        ]
        if not slots:
            return 0
        slot = slots[self._prefill_rr % len(slots)]
        self._prefill_rr += 1
        sl = self._slots[slot]
        req = sl.req
        plen = req.prompt.size
        start = sl.prefill_pos
        chunk = req.prompt[start:start + s.prefill_chunk]
        real = chunk.size
        if real < s.prefill_chunk:
            chunk = np.pad(chunk, (0, s.prefill_chunk - real))
        jnp = self._jnp
        self._pool, logits = self._prefill_jit(
            self._params,
            self._pool,
            jnp.asarray(chunk[None], jnp.int32),
            jnp.asarray(self._tables[slot]),
            jnp.int32(start),
        )
        sl.prefill_pos += real
        self.total_prefill_tokens += real
        self.block_pool.note_filled(req.req_id, sl.prefill_pos)
        if sl.prefill_pos >= plen:
            # sample the first new token from the last REAL prompt
            # position's logits (it lives inside this chunk)
            tok = self._sample_jit(
                logits[0, plen - 1 - start],
                jnp.asarray(self._keys[slot]),
                jnp.int32(plen),
            )
            sl.phase = "decode"
            self._positions[slot] = plen
            self._active[slot] = True
            self._next_token[slot] = int(tok)
            if self._append_token(slot, int(tok), finished):
                pass  # finished on its very first token
        return real

    def _decode_once(self, finished: List[GenResult]) -> int:
        """One decode iteration over every active lane; returns the
        number of tokens sampled."""
        decoding = [
            i for i, sl in enumerate(self._slots)
            if sl.phase == "decode"
        ]
        if not decoding:
            return 0
        jnp = self._jnp
        self._pool, nxt = self._decode_jit(
            self._params,
            self._pool,
            jnp.asarray(self._next_token),
            jnp.asarray(self._tables),
            jnp.asarray(self._positions),
            jnp.asarray(self._active),
            jnp.asarray(self._keys),
        )
        nxt = np.asarray(nxt)
        sampled = 0
        for slot in decoding:
            self._positions[slot] += 1
            self.block_pool.note_filled(
                self._slots[slot].req.req_id,
                int(self._positions[slot]),
            )
            tok = int(nxt[slot])
            sampled += 1
            if not self._append_token(slot, tok, finished):
                self._next_token[slot] = tok
        return sampled

    def step(self) -> List[GenResult]:
        """One scheduler iteration: admit -> one prefill chunk -> one
        decode step.  Returns the sequences that finished."""
        if self._params is None:
            raise RuntimeError(
                "sync_weights() before step() — the scheduler has no "
                "params to serve with"
            )
        t0 = time.monotonic()
        emit = self._events is not None and self._events.enabled
        finished: List[GenResult] = []
        self._admit()
        pre_t0 = time.monotonic()
        pre = self._prefill_one(finished)
        pre_t1 = time.monotonic()
        self._admit()  # a first-token EOS may have freed a slot
        dec_t0 = time.monotonic()
        dec = self._decode_once(finished)
        dec_t1 = time.monotonic()
        self._admit()
        self.iterations += 1
        if emit and (pre or dec):
            from dlrover_tpu.observability.events import anchored_now

            if pre:
                self._events.complete(
                    "prefill",
                    anchored_now(pre_t0),
                    pre_t1 - pre_t0,
                    tokens=pre,
                )
            if dec:
                self._events.complete(
                    "decode",
                    anchored_now(dec_t0),
                    dec_t1 - dec_t0,
                    new_tokens=dec,
                )
            dur = max(time.monotonic() - t0, 1e-9)
            self._events.complete(
                "serve_step",
                anchored_now(t0),
                dur,
                tokens=pre,
                new_tokens=dec,
                throughput_tps=round((pre + dec) / dur, 2),
            )
        return finished

    def run(self, max_iterations: int = 1_000_000) -> List[GenResult]:
        """Drive until idle (offline / bench mode)."""
        out: List[GenResult] = []
        for _ in range(max_iterations):
            if self.idle:
                break
            out.extend(self.step())
        return out

    def drain(self) -> List[GenRequest]:
        """Stop admitting and evict every in-flight sequence, handing
        back requeueable requests (the PR-9 preemption-drain dual for
        serving: nothing in flight is lost, it re-runs elsewhere and
        — sampling being (seed, position)-pure — reproduces the same
        tail)."""
        self.draining = True
        requeue: List[GenRequest] = list(self._queue)
        self._queue.clear()
        for slot, sl in enumerate(self._slots):
            if sl.req is None:
                continue
            self.block_pool.free(sl.req.req_id)
            self._tables[slot] = 0
            self._positions[slot] = 0
            self._active[slot] = False
            requeue.append(sl.req)
            self._slots[slot] = _Slot()
        if requeue:
            logger.info(
                "scheduler drained: %d request(s) handed back",
                len(requeue),
            )
        return requeue
